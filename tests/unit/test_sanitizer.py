"""Program-sanitizer tests: fixture HLO per rule, planted-defect REAL
programs, and the serving-decode tier-1 gate.

Three layers, mirroring test_collective_audit.py's structure:

1. Hand-built HLO fixtures, one planted defect per rule — pins each rule's
   detection, severity, and byte attribution without compiling anything.
2. REAL planted-defect programs (``tools/program_lint.py``'s self-test
   pair): the defective twin must light up every rule through an actual
   lower+compile; the clean twin must produce nothing above info.
3. The serving decode program, audited end to end and held to the
   checked-in ``serving-decode/8/bf16`` budget — the tier-1 fence for the
   paged-KV / flash-decode rewrites ROADMAP items 1-2 will make. (The tiny
   TRAINING preset's sanitizer gate lives in test_collective_audit.py,
   riding the cached tiny-test audit.)
"""

import json
import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools"))

from deepspeed_tpu.profiling.sanitizer import (  # noqa: E402
    check_sanitizer_budgets,
    count_at_or_above,
    estimate_peak_hbm,
    merge_reports,
    parse_entry_outputs,
    parse_entry_params,
    parse_input_output_alias,
    rule_recompile_hazard,
    sanitize_hlo,
    sanitize_jaxpr,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BUDGETS = json.load(open(os.path.join(REPO, "tools", "collective_budgets.json")))


# ---------------------------------------------------------------------------
# 1. fixture HLO, one planted defect per rule
# ---------------------------------------------------------------------------

HLO_DTYPE_LEAK = """
HloModule jit_step, entry_computation_layout={(bf16[64,64]{1,0})->bf16[64,64]{1,0}}

body.1 {
  p.1 = f32[8]{0} parameter(0)
  x.1 = f32[64,64]{1,0} broadcast(p.1), dimensions={0}
  y.1 = f32[64,64]{1,0} broadcast(p.1), dimensions={0}
  w.1 = bf16[64,64]{1,0} all-gather(q.1), channel_id=1, dimensions={0}
  d.1 = f32[64,64]{1,0} dot(x.1, y.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/leaky/dot_general"}
  g.1 = f32[64,64]{1,0} all-gather(s.1), channel_id=2, dimensions={0}
  d.2 = bf16[64,64]{1,0} dot(w.1, w.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT r.1 = f32[8]{0} add(p.1, p.1)
}

ENTRY main.9_spmd {
  a.1 = bf16[64,64]{1,0} parameter(0)
  wl.1 = f32[8]{0} while(init.1), condition=cond.9, body=body.1
  ROOT out.1 = bf16[64,64]{1,0} copy(a.1)
}
"""


def test_dtype_leak_attribution_and_trip():
    r = sanitize_hlo(HLO_DTYPE_LEAK, {"compute_dtype": "bf16"},
                     n_devices=8, loop_trip_count=24)
    leaks = [f for f in r["findings"] if f["rule"] == "dtype-leak"]
    # the f32 dot AND the f32 all-gather, not the bf16 dot/gather
    assert {f["instruction"] for f in leaks} == {"d.1", "g.1"}
    s = r["summary"]
    # both dots are 64x64x64 matmuls in the x24 while body; half the flops f32
    assert s["f32_dot_flops_frac"] == pytest.approx(0.5)
    assert s["total_dot_flops"] == pytest.approx(2 * 2 * 64 ** 3 * 24)
    # one f32 dot = 50% of dot flops >= the 1% warn threshold -> escalated
    d = next(f for f in leaks if f["instruction"] == "d.1")
    assert d["severity"] == "warning"
    assert d["op_name"] == "jit(f)/leaky/dot_general"
    # collective wire: all-gather in-body, ring accounting x24 (no groups ->
    # single-participant fallback frac=1.0 is not used: default_n=1 -> frac 1)
    assert s["f32_collective_wire_bytes"] > 0


def test_dtype_leak_allowlist_demotes():
    r = sanitize_hlo(HLO_DTYPE_LEAK,
                     {"compute_dtype": "bf16", "allow": ["dtype-leak:leaky"]},
                     n_devices=8, loop_trip_count=1)
    d = next(f for f in r["findings"] if f["instruction"] == "d.1")
    assert d["allowed"] and d["severity"] == "info"
    # allowed findings drop out of the summary counters
    assert all(f["severity"] != "warning" or f["instruction"] != "d.1"
               for f in r["findings"])
    # fp32-configured program: f32 compute is not a leak at all
    r32 = sanitize_hlo(HLO_DTYPE_LEAK, {"compute_dtype": "f32"}, 8)
    assert not [f for f in r32["findings"] if f["rule"] == "dtype-leak"]


HLO_DONATION = """
HloModule jit_step, input_output_alias={ {0}: (0, {}, may-alias) }, entry_computation_layout={(f32[100]{0}, f32[200]{0}, f32[50]{0})->(f32[100]{0}, f32[200]{0})}

ENTRY main.5_spmd {
  p0.1 = f32[100]{0} parameter(0), metadata={op_name="params"}
  p1.1 = f32[200]{0} parameter(1), metadata={op_name="opt_state"}
  p2.1 = f32[50]{0} parameter(2), metadata={op_name="batch"}
  a.1 = f32[100]{0} add(p0.1, p0.1)
  b.1 = f32[200]{0} multiply(p1.1, p1.1)
  ROOT t.1 = (f32[100]{0}, f32[200]{0}) tuple(a.1, b.1)
}
"""


def test_donation_rule_flags_matching_unaliased_input():
    assert parse_input_output_alias(HLO_DONATION) == {0: 0}
    assert [p["op_name"] for p in parse_entry_params(HLO_DONATION)] == \
        ["params", "opt_state", "batch"]
    assert parse_entry_outputs(HLO_DONATION) == \
        [("f32", "100"), ("f32", "200")]
    r = sanitize_hlo(HLO_DONATION, {"compute_dtype": "f32",
                                    "donation_bytes_threshold": 100})
    d = [f for f in r["findings"] if f["rule"] == "donation"]
    # opt_state (f32[200], un-aliased, matches un-aliased output #1) fires;
    # params is aliased, batch (f32[50]) matches no output shape
    assert len(d) == 1
    assert d[0]["op_name"] == "opt_state"
    assert d[0]["bytes"] == 800 and d[0]["output_index"] == 1
    assert d[0]["severity"] == "warning"
    assert r["summary"]["undonated_candidate_bytes"] == 800
    assert r["summary"]["n_aliased_params"] == 1
    # above the error threshold the severity escalates
    r2 = sanitize_hlo(HLO_DONATION, {"compute_dtype": "f32",
                                     "donation_bytes_threshold": 100,
                                     "donation_error_bytes": 500})
    d2 = [f for f in r2["findings"] if f["rule"] == "donation"]
    assert d2[0]["severity"] == "error"


HLO_TRANSFER = """
HloModule jit_step, entry_computation_layout={(f32[10]{0})->f32[10]{0}}

ENTRY main.7_spmd {
  p0.1 = f32[10]{0} parameter(0)
  tk.1 = token[] after-all()
  of.1 = token[] outfeed(p0.1, tk.1), outfeed_config="x"
  cc.1 = (f32[1]{0}) custom-call(p0.1), custom_call_target="xla_python_cpu_callback", custom_call_has_side_effect=true
  h.1 = f32[10]{0:S(5)} copy(p0.1)
  ROOT r.1 = f32[10]{0} add(p0.1, p0.1)
}
"""


def test_transfer_rule_fires_on_every_host_path():
    r = sanitize_hlo(HLO_TRANSFER, {"compute_dtype": "f32"})
    t = [f for f in r["findings"] if f["rule"] == "transfer"]
    assert {f["instruction"] for f in t} == {"of.1", "cc.1", "h.1"}
    assert all(f["severity"] == "error" for f in t)
    assert r["summary"]["transfer_count"] == 3
    assert r["summary"]["max_severity"] == "error"


HLO_SHARDING = """
HloModule jit_step, entry_computation_layout={(f32[300000]{0})->f32[300000]{0}}

body.2 {
  p.1 = f32[8]{0} parameter(0)
  ag.1 = bf16[1048576]{0} all-gather(q.1), channel_id=1, dimensions={0}
  ROOT r.1 = f32[8]{0} add(p.1, p.1)
}

ENTRY main.11_spmd {
  big.1 = f32[300000]{0} parameter(0), sharding={replicated}, metadata={op_name="frozen_table"}
  small.1 = f32[10]{0} parameter(1), sharding={replicated}
  sharded.1 = f32[4096]{0} parameter(2), sharding={devices=[8]<=[8]}
  wl.1 = f32[8]{0} while(init.1), condition=cond.11, body=body.2
  eg.1 = f32[1048576]{0} all-gather(sharded.1), channel_id=2, dimensions={0}
  ROOT out.1 = f32[300000]{0} copy(big.1)
}
"""


def test_sharding_rule_replicated_and_entry_gathers():
    r = sanitize_hlo(HLO_SHARDING, {"compute_dtype": "f32"}, n_devices=8)
    s = [f for f in r["findings"] if f["rule"] == "sharding"]
    # the 1.2 MB replicated table fires; the 40 B replicated scalar and the
    # properly sharded param do not
    rep = [f for f in s if "replicated" in f["message"]]
    assert len(rep) == 1 and rep[0]["op_name"] == "frozen_table"
    assert rep[0]["bytes"] == 300000 * 4
    # the 4 MB ENTRY-scope gather fires; the while-body (gather island) one
    # does not
    eg = [f for f in s if "ENTRY scope" in f["message"]]
    assert len(eg) == 1 and eg[0]["instruction"] == "eg.1"
    assert r["summary"]["replicated_bytes"] == 300000 * 4
    assert r["summary"]["entry_gather_bytes"] == 1048576 * 4


HLO_PEAK = """
HloModule jit_step, entry_computation_layout={(f32[100]{0}, f32[200]{0})->(f32[100]{0}, f32[200]{0})}

ENTRY main.3_spmd {
  p0.1 = f32[100]{0} parameter(0)
  p1.1 = f32[200]{0} parameter(1)
  a.1 = f32[100]{0} add(p0.1, p0.1)
  b.1 = f32[200]{0} multiply(p1.1, p1.1)
  c.1 = f32[100]{0} add(a.1, a.1)
  ROOT t.1 = (f32[100]{0}, f32[200]{0}) tuple(c.1, b.1)
}
"""


def test_peak_hbm_liveness_walk_exact():
    p = estimate_peak_hbm(HLO_PEAK)
    # args: 400 + 800; intermediates peak at c.1: a(400)+b(800)+c(400)
    assert p["argument_bytes"] == 1200
    assert p["transient_peak_bytes"] == 1600
    assert p["estimate_bytes"] == 2800
    assert p["peak_instruction"] == "c.1"


def test_peak_hbm_charges_callee_as_transient():
    hlo = """
HloModule jit_step, entry_computation_layout={(f32[100]{0})->f32[100]{0}}

body.3 {
  bp.1 = f32[100]{0} parameter(0)
  big.1 = f32[1000]{0} broadcast(bp.1), dimensions={0}
  red.1 = f32[100]{0} slice(big.1), slice={[0:100]}
  ROOT br.1 = f32[100]{0} add(red.1, red.1)
}

ENTRY main.4_spmd {
  p0.1 = f32[100]{0} parameter(0)
  wl.1 = f32[100]{0} while(p0.1), condition=cond.4, body=body.3
  ROOT o.1 = f32[100]{0} copy(wl.1)
}
"""
    p = estimate_peak_hbm(hlo)
    # while result is a view, but its body's own peak (big 4000 live
    # together with red 400; big frees before br allocates) lands as a
    # transient at the call site
    assert p["argument_bytes"] == 400
    assert p["transient_peak_bytes"] == 4400
    assert p["peak_instruction"] == "wl.1"


def test_recompile_hazard_consts_and_scalar_args():
    jaxpr = types.SimpleNamespace(
        consts=[np.zeros((600, 600), np.float32),   # 1.44 MB: fires
                np.zeros((4,), np.float32)])        # 16 B: quiet
    fs, stats = rule_recompile_hazard(jaxpr, example_args=None)
    assert len(fs) == 1 and fs[0]["severity"] == "warning"
    assert stats["baked_const_bytes"] == 600 * 600 * 4
    import jax.numpy as jnp

    r = sanitize_jaxpr(jaxpr, example_args=(jnp.ones((2,)), 0.5, {"t": 3}))
    scal = [f for f in r["findings"] if "scalar" in f["message"]]
    assert len(scal) == 2  # the float AND the int leaf, not the array
    assert r["summary"]["python_scalar_args"] == 2


def test_budget_checks_and_fail_on():
    r = sanitize_hlo(HLO_TRANSFER, {"compute_dtype": "f32"})
    v = check_sanitizer_budgets(r, {"transfer_count_max": 0})
    assert len(v) == 1 and "host transfers" in v[0]
    assert not check_sanitizer_budgets(r, {"transfer_count_max": 3})
    v = check_sanitizer_budgets(r, {"errors_max": 0})
    assert len(v) == 1 and "error-severity" in v[0]
    assert count_at_or_above(r["findings"], "error") == 3
    assert count_at_or_above(r["findings"], "info") >= 3
    # and through the top-level check_budgets() seam, as the tier-1 gate
    # consumes it (a report with a sanitizer section + a budget with a
    # sanitizer sub-dict)
    from deepspeed_tpu.profiling.collectives import check_budgets

    report = {"collectives": {"all-gather": {"wire_bytes": 0.0,
                                             "by_dtype": {}}},
              "total_wire_bytes": 0.0, "fp32_param_bytes_per_chip": 0.0,
              "sanitizer": r}
    v = check_budgets(report, {"sanitizer": {"transfer_count_max": 0}})
    assert len(v) == 1 and "host transfers" in v[0]
    # reports predating the sanitizer stay checkable
    del report["sanitizer"]
    assert not check_budgets(report, {"sanitizer": {"transfer_count_max": 0}})


def test_merge_reports_combines_views():
    hlo_r = sanitize_hlo(HLO_TRANSFER, {"compute_dtype": "f32"})
    jax_r = sanitize_jaxpr(
        types.SimpleNamespace(consts=[np.zeros((600, 600), np.float32)]))
    m = merge_reports(hlo_r, jax_r)
    assert m["summary"]["transfer_count"] == 3
    assert m["summary"]["baked_const_bytes"] == 600 * 600 * 4
    assert m["summary"]["counts"]["error"] == 3
    assert m["summary"]["counts"]["warning"] == 1
    assert "peak_hbm" in m


# ---------------------------------------------------------------------------
# 2. REAL planted-defect programs (program_lint's self-test pair)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def planted(devices8):
    from program_lint import _planted_program

    return _planted_program(clean=False)


def test_planted_program_lights_up_every_rule(devices8, planted):
    """The acceptance pin: all five defect classes detected on a real
    compiled program (dtype leak, missing donation, host transfer,
    replicated tensor, recompile hazard) — plus the entry-scope gather."""
    san = planted["sanitizer"]
    fired = {f["rule"] for f in san["findings"] if not f.get("allowed")}
    assert {"dtype-leak", "donation", "transfer", "sharding",
            "recompile-hazard"} <= fired
    assert san["summary"]["counts"]["error"] >= 1          # the transfer
    assert san["summary"]["transfer_count"] == 1
    assert san["summary"]["f32_dot_flops_frac"] == pytest.approx(1.0)
    # the undonated 512 KiB weight is attributed with its bytes
    d = next(f for f in san["findings"] if f["rule"] == "donation")
    assert d["bytes"] * 8 == 512 * 512 * 2  # per-chip local shard
    assert san["summary"]["replicated_bytes"] == 512 * 512 * 4
    assert san["summary"]["baked_const_bytes"] == 512 * 512 * 4
    assert san["summary"]["python_scalar_args"] == 1
    assert count_at_or_above(san["findings"], "error") >= 1


def test_clean_program_zero_findings_above_info(devices8):
    from program_lint import _planted_program

    report = _planted_program(clean=True)
    san = report["sanitizer"]
    assert count_at_or_above(san["findings"], "warning") == 0
    assert san["summary"]["transfer_count"] == 0
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert san["summary"]["f32_dot_flops_frac"] == 0.0


# ---------------------------------------------------------------------------
# 3. the serving decode program, held to the checked-in budget (tier-1)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def decode_report(devices8):
    """Same geometry as tools/program_lint.py --program decode defaults
    (tiny-test dims, 4 slots x 64 KV window) so the committed
    serving-decode/8/bf16 budget's observed values are THIS program's."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(
        vocab_size=512, max_seq_len=64, n_layers=4, n_heads=4,
        d_model=128, d_ff=256, compute_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(
        model=model,
        config={"dtype": "bfloat16", "max_tokens": 64,
                "serving": {"n_slots": 4, "max_len": 64,
                            "virtual_clock": True}})
    report = engine.decode_program_report()
    yield report
    engine.destroy()


def test_serving_decode_within_sanitizer_budget(decode_report):
    from deepspeed_tpu.profiling.collectives import check_budgets

    v = check_budgets(decode_report, BUDGETS["serving-decode/8/bf16"])
    assert not v, v
    san = decode_report["sanitizer"]
    # nothing above info once the QK f32 einsum is allowlisted
    assert count_at_or_above(san["findings"], "warning") == 0


@pytest.fixture(scope="module")
def decode_report_paged(devices8):
    """tools/program_lint.py --program decode --paged geometry: the PAGED
    decode program (block-table gathers + pool writeback) held to the
    checked-in serving-decode-paged/8/bf16 budget — the fence for ROADMAP
    item 1's rewrite, enforced tier-1 alongside the dense gate."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(
        vocab_size=512, max_seq_len=64, n_layers=4, n_heads=4,
        d_model=128, d_ff=256, compute_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(
        model=model,
        config={"dtype": "bfloat16", "max_tokens": 64,
                "serving": {"n_slots": 4, "max_len": 64,
                            "virtual_clock": True,
                            "kv_pool": {"enabled": True,
                                        "block_size": 16}}})
    report = engine.decode_program_report()
    yield report
    engine.destroy()


def test_serving_decode_paged_within_sanitizer_budget(decode_report_paged):
    from deepspeed_tpu.profiling.collectives import check_budgets

    v = check_budgets(decode_report_paged,
                      BUDGETS["serving-decode-paged/8/bf16"])
    assert not v, v
    san = decode_report_paged["sanitizer"]
    assert count_at_or_above(san["findings"], "warning") == 0
    # full donation of the paged pool state: k/v pool + block table +
    # per-slot cursors/rng/knobs all alias outputs, zero host transfers —
    # the paged rewrite kept the program inside the same fence
    assert san["summary"]["n_aliased_params"] == 12
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert san["summary"]["transfer_count"] == 0


@pytest.fixture(scope="module")
def decode_report_fused(devices8):
    """tools/program_lint.py --program decode --paged --attention-backend
    fused geometry: the PAGED decode program through the split-KV
    flash-decode kernel (block-table walk IN-KERNEL, no dense per-slot
    view) held to the checked-in serving-decode-fused/8/bf16 budget —
    the fence for ROADMAP item 1's fused rewrite, enforced tier-1
    alongside the gather gate."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(
        vocab_size=512, max_seq_len=64, n_layers=4, n_heads=4,
        d_model=128, d_ff=256, compute_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(
        model=model,
        config={"dtype": "bfloat16", "max_tokens": 64,
                "serving": {"n_slots": 4, "max_len": 64,
                            "virtual_clock": True,
                            "kv_pool": {"enabled": True,
                                        "block_size": 16,
                                        "attention_backend": "fused"}}})
    assert engine.serving.attn_backend == "fused"
    report = engine.decode_program_report()
    yield report
    engine.destroy()


def test_serving_decode_fused_within_sanitizer_budget(decode_report_fused):
    from deepspeed_tpu.profiling.collectives import check_budgets

    v = check_budgets(decode_report_fused,
                      BUDGETS["serving-decode-fused/8/bf16"])
    assert not v, v
    san = decode_report_fused["sanitizer"]
    assert count_at_or_above(san["findings"], "warning") == 0
    # the fused program is held to the SAME donation/transfer fence as the
    # gather path (pool k/v + block table + cursors/rng/knobs all aliased)
    assert san["summary"]["n_aliased_params"] == 12
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert san["summary"]["transfer_count"] == 0
    # the table/cursors ride into the kernel as scalar-prefetch operands,
    # never as Python scalars: compiles once per (model, pool) config
    assert san["summary"].get("python_scalar_args", 0) == 0


def test_fused_peak_hbm_ceiling_below_gather_budget(decode_report_fused):
    """The whole point of the kernel is DELETING the dense-view transient:
    the fused budget's peak-HBM ceiling sits strictly below the gather
    budget's, and the fused program's liveness estimate fits it. (The
    view's absence itself — 0 view-shaped gathers in the lowered program —
    is pinned in test_paged_attention.py.)"""
    fused_cap = BUDGETS["serving-decode-fused/8/bf16"]["sanitizer"][
        "peak_hbm_gb_max"]
    gather_cap = BUDGETS["serving-decode-paged/8/bf16"]["sanitizer"][
        "peak_hbm_gb_max"]
    assert fused_cap < gather_cap
    est = decode_report_fused["sanitizer"]["peak_hbm"]["estimate_bytes"]
    assert est / 1e9 <= fused_cap


@pytest.fixture(scope="module")
def prefill_chunked_report(devices8):
    """tools/program_lint.py --program prefill-chunked geometry: the chunked
    suffix-prefill program (one full chunk's bucket at a traced start
    position against a donated partial cache) held to the checked-in
    serving-prefill-chunked/8/bf16 budget — the fence for the chunked-
    prefill path, enforced tier-1 alongside the decode gates."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(
        vocab_size=512, max_seq_len=64, n_layers=4, n_heads=4,
        d_model=128, d_ff=256, compute_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(
        model=model,
        config={"dtype": "bfloat16", "max_tokens": 64,
                "serving": {"n_slots": 4, "max_len": 64,
                            "virtual_clock": True,
                            "chunked_prefill": {"enabled": True,
                                                "chunk_size": 16}}})
    report = engine.prefill_chunk_report()
    yield report
    engine.destroy()


def test_serving_prefill_chunked_within_sanitizer_budget(
        prefill_chunked_report):
    from deepspeed_tpu.profiling.collectives import check_budgets

    v = check_budgets(prefill_chunked_report,
                      BUDGETS["serving-prefill-chunked/8/bf16"])
    assert not v, v
    san = prefill_chunked_report["sanitizer"]
    assert count_at_or_above(san["findings"], "warning") == 0
    # the donation pin chunked prefill depends on: the partial b=1 cache
    # (k + v) aliases the output, so chunk N+1 reuses chunk N's buffers —
    # a chunked prefill never holds two copies of the request's cache
    assert san["summary"]["n_aliased_params"] == 2
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert san["summary"]["transfer_count"] == 0
    # start_pos / true_len are TRACED: one compiled program per chunk
    # bucket no matter where in the prompt the chunk starts
    assert san["summary"].get("python_scalar_args", 0) == 0
    assert san["summary"].get("baked_const_bytes", 0) == 0


@pytest.fixture(scope="module")
def verify_report(devices8):
    """tools/program_lint.py --program verify geometry: the speculative
    one-forward verify program (k+1 positions per slot against the paged
    pool, drafts/draft_len traced) held to the checked-in
    serving-verify/8/bf16 budget — the fence for the speculative-decoding
    subsystem, enforced tier-1 alongside the decode/prefill gates."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    model = CausalLM(TransformerConfig(
        vocab_size=512, max_seq_len=64, n_layers=4, n_heads=4,
        d_model=128, d_ff=256, compute_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(
        model=model,
        config={"dtype": "bfloat16", "max_tokens": 64,
                "serving": {"n_slots": 4, "max_len": 64,
                            "virtual_clock": True,
                            "kv_pool": {"enabled": True,
                                        "block_size": 16},
                            "speculative": {"enabled": True, "k": 4}}})
    report = engine.verify_program_report()
    yield report
    engine.destroy()


def test_serving_verify_within_sanitizer_budget(verify_report):
    from deepspeed_tpu.profiling.collectives import check_budgets

    v = check_budgets(verify_report, BUDGETS["serving-verify/8/bf16"])
    assert not v, v
    san = verify_report["sanitizer"]
    assert count_at_or_above(san["findings"], "warning") == 0
    # the donation pin speculation depends on: the verify step holds ONE
    # copy of the paged pool state (same 12-leaf census as the paged
    # decode program — pool k/v + block table + cursors/rng/knobs), with
    # zero host transfers and the drafts/draft_len TRACED (one compiled
    # program per k, no recompile per draft mix)
    assert san["summary"]["n_aliased_params"] == 12
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert san["summary"]["transfer_count"] == 0
    assert san["summary"].get("python_scalar_args", 0) == 0
    assert san["summary"].get("baked_const_bytes", 0) == 0


def test_serving_decode_slot_state_fully_donated(decode_report):
    """The donation discipline the slot pool depends on: every state leaf
    (KV pool, cursors, rng, sampling knobs — 11 arrays) aliases an output,
    so decode-in-a-loop holds ONE copy of the pool, not two. The only
    un-aliased outputs are the 2 that ran out of same-shape input buffers
    (nxt/done_now duplicates); weights are read-only by design."""
    san = decode_report["sanitizer"]
    assert san["summary"]["n_aliased_params"] == 11
    assert san["summary"]["undonated_candidate_bytes"] == 0
    assert not [f for f in san["findings"]
                if f["rule"] == "donation" and not f.get("allowed")]


def test_serving_decode_no_transfers_or_hazards(decode_report):
    san = decode_report["sanitizer"]
    assert san["summary"]["transfer_count"] == 0
    assert san["summary"].get("baked_const_bytes", 0) == 0
    assert san["summary"].get("python_scalar_args", 0) == 0
    p = san["peak_hbm"]
    assert 0 < p["estimate_bytes"] < \
        BUDGETS["serving-decode/8/bf16"]["sanitizer"]["peak_hbm_gb_max"] * 1e9
