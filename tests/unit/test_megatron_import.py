"""Megatron-LM checkpoint import: TP merge parity.

Reference behavior being matched: ``runtime/state_dict_factory.py``
``MegatronSDLoader`` merge rules (qkv head-grouped cat, column-parallel cat
axis 0, row-parallel cat axis 1, vocab-parallel embedding cat + pad trim).
The test builds ONE logical model, saves it both as a tp=1 and a tp=2
Megatron checkpoint, and requires the two loads to be bit-identical — the
merge is correct iff splitting and re-merging is the identity.
"""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.module_inject.megatron import (
    load_megatron_checkpoint,
    megatron_model_from_checkpoint,
)

D, NH, HD, FF, L, VOCAB, SEQ = 32, 4, 8, 64, 2, 96, 16


def _full_state(rng):
    """The logical (unsplit) Megatron transformer state, torch layout."""
    t = lambda *s: torch.tensor(rng.standard_normal(s), dtype=torch.float32)
    trans = {"final_layernorm.weight": t(D), "final_layernorm.bias": t(D)}
    for i in range(L):
        p = f"layers.{i}."
        trans.update({
            p + "input_layernorm.weight": t(D),
            p + "input_layernorm.bias": t(D),
            # heads-major (checkpoint_version 3) layout [nh, 3, hd, d]
            p + "attention.query_key_value.weight": t(NH, 3, HD, D),
            p + "attention.query_key_value.bias": t(NH, 3, HD),
            p + "attention.dense.weight": t(D, D),
            p + "attention.dense.bias": t(D),
            p + "post_attention_layernorm.weight": t(D),
            p + "post_attention_layernorm.bias": t(D),
            p + "mlp.dense_h_to_4h.weight": t(FF, D),
            p + "mlp.dense_h_to_4h.bias": t(FF),
            p + "mlp.dense_4h_to_h.weight": t(D, FF),
            p + "mlp.dense_4h_to_h.bias": t(D),
        })
    emb = {
        "word_embeddings": {"weight": t(VOCAB, D)},
        "position_embeddings": {"weight": t(SEQ, D)},
    }
    return emb, trans


def _save_rank(dirpath, rank, emb, trans):
    rd = os.path.join(dirpath, f"mp_rank_{rank:02d}")
    os.makedirs(rd, exist_ok=True)
    torch.save(
        {"checkpoint_version": 3.0,
         "model": {"language_model": {"embedding": emb,
                                      "transformer": trans}}},
        os.path.join(rd, "model_optim_rng.pt"))


def _save_split(dirpath, emb, trans, tp):
    """Split the logical state the way Megatron's parallel layers shard it."""
    for r in range(tp):
        et, tt = {}, {}
        w = emb["word_embeddings"]["weight"]
        assert w.shape[0] % tp == 0
        sl = slice(r * w.shape[0] // tp, (r + 1) * w.shape[0] // tp)
        et["word_embeddings"] = {"weight": w[sl].clone()}
        et["position_embeddings"] = {
            "weight": emb["position_embeddings"]["weight"].clone()}
        for k, v in trans.items():
            if "query_key_value" in k:
                h = NH // tp
                vv = v[r * h:(r + 1) * h]          # heads-major slice
                tt[k] = vv.reshape((h * 3 * HD,) + tuple(v.shape[3:])).clone()
            elif "dense_h_to_4h" in k:              # column-parallel
                n = v.shape[0] // tp
                tt[k] = v[r * n:(r + 1) * n].clone()
            elif k.endswith(("attention.dense.weight",
                             "mlp.dense_4h_to_h.weight")):  # row-parallel
                n = v.shape[1] // tp
                tt[k] = v[:, r * n:(r + 1) * n].clone()
            else:                                   # replicated
                tt[k] = v.clone()
        _save_rank(dirpath, r, et, tt)


@pytest.fixture(scope="module")
def ckpts(tmp_path_factory):
    rng = np.random.default_rng(0)
    emb, trans = _full_state(rng)
    d1 = str(tmp_path_factory.mktemp("meg_tp1"))
    d2 = str(tmp_path_factory.mktemp("meg_tp2"))
    # tp=1 save keeps the flat [3*nh*hd, d] qkv a real checkpoint has
    flat = dict(trans)
    for k in list(flat):
        if "query_key_value" in k:
            v = flat[k]
            flat[k] = v.reshape((NH * 3 * HD,) + tuple(v.shape[3:]))
    _save_rank(d1, 0, emb, flat)
    _save_split(d2, emb, trans, tp=2)
    return d1, d2


def _cfg():
    return TransformerConfig(
        vocab_size=VOCAB, max_seq_len=SEQ, n_layers=L, n_heads=NH,
        d_model=D, d_ff=FF)


def test_tp2_merge_equals_tp1(ckpts):
    d1, d2 = ckpts
    v1, _ = load_megatron_checkpoint(d1, config=_cfg())
    v2, _ = load_megatron_checkpoint(d2, config=_cfg())
    import jax

    leaves1, tree1 = jax.tree_util.tree_flatten(v1)
    leaves2, tree2 = jax.tree_util.tree_flatten(v2)
    assert tree1 == tree2
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_forward_runs_and_vocab_trim(ckpts):
    _, d2 = ckpts
    # trim: ask for a smaller vocab than the (padded) checkpoint vocab
    model, values = megatron_model_from_checkpoint(
        d2, config=_cfg(), vocab_size=VOCAB - 8)
    assert values["wte"]["weight"].shape == (VOCAB - 8, D)
    import jax.numpy as jnp

    ids = jnp.asarray(np.arange(8, dtype=np.int32)[None, :])
    logits = model.apply(values, ids)
    assert logits.shape == (1, 8, VOCAB - 8)
    assert np.isfinite(np.asarray(logits)).all()


def test_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_megatron_checkpoint(str(tmp_path))


def test_checkpoint_version0_qkv_major_layout(ckpts, tmp_path):
    """Pre-versioning checkpoints store qkv as [3, heads*hd, d] (qkv-major);
    absent 'checkpoint_version' must select that layout (reference
    state_dict_factory.py:427 get(..., 0)) — defaulting to the heads-major
    reshape would silently scramble q/k/v."""
    d1, _ = ckpts
    v3, _ = load_megatron_checkpoint(d1, config=_cfg())

    # rebuild the same logical state in v0 layout: [nh,3,hd,X] -> [3,nh*hd,X]
    rd = os.path.join(str(tmp_path), "mp_rank_00")
    os.makedirs(rd)
    src = torch.load(os.path.join(d1, "mp_rank_00", "model_optim_rng.pt"),
                     weights_only=False)
    lm = src["model"]["language_model"]
    trans = {}
    for key, val in lm["transformer"].items():
        if "query_key_value" in key:
            x = val.reshape((NH, 3, HD) + tuple(val.shape[1:]))
            x = x.permute(1, 0, 2, *range(3, x.ndim))
            trans[key] = x.reshape((3 * NH * HD,) + tuple(val.shape[1:])).clone()
        else:
            trans[key] = val
    torch.save({"model": {"language_model": {
        "embedding": lm["embedding"], "transformer": trans}}},
        os.path.join(rd, "model_optim_rng.pt"))  # NO checkpoint_version key

    v0, _ = load_megatron_checkpoint(str(tmp_path), config=_cfg())
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(v3),
                    jax.tree_util.tree_leaves(v0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
