"""Aux subsystem tests: elasticity, flops profiler, quantizer/compression,
curriculum scheduler, data sampler. Mirrors reference tests
(``tests/unit/elasticity/test_elastic.py``, ``tests/unit/ops/quantizer``,
``tests/unit/runtime/test_data_efficiency.py``)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.elasticity import (
    compute_elastic_config, get_compatible_gpus_v01, ElasticityError)
from deepspeed_tpu.ops.quantizer import (
    quantize, dequantize, fake_quantize, quantization_error)
from deepspeed_tpu.compression import init_compression, redundancy_clean
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, DeepSpeedDataSampler)
from deepspeed_tpu.profiling import FlopsProfiler, transformer_train_flops


# ---------------------------------------------------------------------------------
# elasticity (reference tests/unit/elasticity/test_elastic.py)
# ---------------------------------------------------------------------------------
def test_elastic_v01_basic():
    batch, valid = get_compatible_gpus_v01([2, 4, 6], max_acceptable_batch_size=10000)
    # every valid world size must actually divide batch with some micro batch
    for w in valid[:50]:
        assert any(batch % (m * w) == 0 for m in [2, 4, 6])
    assert batch <= 10000


def test_elastic_compute_config():
    ds_config = {"elasticity": {
        "enabled": True, "max_train_batch_size": 2000,
        "micro_batch_sizes": [2, 4, 6], "min_gpus": 1, "max_gpus": 10000,
        "version": 0.1}}
    batch, valid = compute_elastic_config(ds_config)
    assert batch > 0 and len(valid) > 0
    # world-size compatibility check + micro batch resolution
    w = valid[len(valid) // 2]
    b2, v2, micro = compute_elastic_config(ds_config, world_size=w,
                                           return_microbatch=True)
    assert b2 == batch
    assert (batch // w) % micro == 0


def test_elastic_incompatible_world_size():
    ds_config = {"elasticity": {
        "enabled": True, "max_train_batch_size": 4,
        "micro_batch_sizes": [4], "min_gpus": 1, "max_gpus": 1}}
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config, world_size=3)


def test_elastic_disabled_raises():
    with pytest.raises(ElasticityError):
        compute_elastic_config({"elasticity": {"enabled": False,
                                               "max_train_batch_size": 100}})


# ---------------------------------------------------------------------------------
# quantizer (reference tests/unit/ops/quantizer)
# ---------------------------------------------------------------------------------
def test_quantize_roundtrip_error_small():
    x = np.random.RandomState(0).randn(128, 64).astype(np.float32)
    q, scale, meta = quantize(x, bits=8, group_size=64)
    assert q.dtype == jnp.int8
    y = dequantize(q, scale, meta)
    assert y.shape == x.shape
    rel = float(jnp.sqrt(jnp.mean((y - x) ** 2)) / jnp.sqrt(jnp.mean(x ** 2)))
    assert rel < 0.01  # int8 groupwise ~0.3% rms error


def test_quantize_int4_coarser_than_int8():
    x = np.random.RandomState(1).randn(64, 64).astype(np.float32)
    e8 = float(quantization_error(x, bits=8))
    e4 = float(quantization_error(x, bits=4))
    assert e4 > e8 > 0


def test_fake_quantize_straight_through_grad():
    x = jnp.asarray(np.random.RandomState(2).randn(32, 32), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(fake_quantize(x, bits=8) * 2.0))(x)
    np.testing.assert_allclose(np.asarray(g), 2.0)  # STE passes grads through


def test_compression_schedule_and_clean():
    params = {"w": jnp.asarray(np.random.RandomState(3).randn(64, 64), jnp.float32),
              "b": jnp.zeros((64,), jnp.float32)}
    runtime = init_compression({"weight_quantization": {
        "enabled": True, "start_bits": 16, "target_bits": 8,
        "quantize_period": 10, "schedule_offset": 5}})
    assert runtime.bits_at(0) is None         # before offset
    assert runtime.bits_at(5) == 16
    assert runtime.bits_at(15) == 8
    assert runtime.bits_at(500) == 8          # floors at target

    out = runtime.compress_params(params, step=25)
    assert out["w"].shape == (64, 64)
    # 1-D params (biases) are never quantized
    np.testing.assert_array_equal(np.asarray(out["b"]), np.asarray(params["b"]))

    cleaned, packed = redundancy_clean(params, {"weight_quantization": {
        "enabled": True, "target_bits": 8}})
    assert "w" in packed and packed["w"]["q"].dtype == np.int8


# ---------------------------------------------------------------------------------
# curriculum (reference tests/unit/runtime/test_data_efficiency.py)
# ---------------------------------------------------------------------------------
def test_curriculum_fixed_linear():
    sched = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert sched.get_current_difficulty() == 8
    d50 = sched.update_difficulty(50)
    assert 8 <= d50 <= 64 and d50 % 8 == 0
    assert sched.update_difficulty(100) == 64
    assert sched.update_difficulty(1000) == 64


def test_curriculum_fixed_discrete():
    sched = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 2, "max_difficulty": 10,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [2, 5, 10], "max_step": [3, 6]}})
    assert sched.update_difficulty(2) == 2
    assert sched.update_difficulty(5) == 5
    assert sched.update_difficulty(100) == 10


def test_curriculum_state_roundtrip():
    cfg = {"curriculum_type": "seqlen", "min_difficulty": 8, "max_difficulty": 64,
           "schedule_type": "fixed_root",
           "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8,
                               "root_degree": 2}}
    a = CurriculumScheduler(cfg)
    a.update_difficulty(30)
    b = CurriculumScheduler(cfg)
    b.set_state(a.get_state())
    assert b.get_current_difficulty() == a.get_current_difficulty()


# ---------------------------------------------------------------------------------
# data sampler
# ---------------------------------------------------------------------------------
def test_sampler_shards_disjoint_and_deterministic():
    samplers = [DeepSpeedDataSampler(100, micro_batch_size=5, data_parallel_rank=r,
                                     data_parallel_size=2, seed=7) for r in range(2)]
    batches = [list(s) for s in samplers]
    assert len(batches[0]) == len(batches[1]) == 10
    for b0, b1 in zip(*batches):
        assert len(b0) == len(b1) == 5
        assert not (set(b0) & set(b1))  # disjoint shards
    # deterministic given the same seed
    again = list(DeepSpeedDataSampler(100, 5, 0, 2, seed=7))
    assert again == batches[0]


def test_sampler_resume_mid_epoch():
    full = list(DeepSpeedDataSampler(64, 4, 0, 2, seed=3))
    half = DeepSpeedDataSampler(64, 4, 0, 2, seed=3)
    it = iter(half)
    first = [next(it) for _ in range(4)]
    resumed = DeepSpeedDataSampler(64, 4, 0, 2, seed=3,
                                   consumed_samples=half.consumed_samples)
    rest = list(resumed)
    assert first + rest == full


# ---------------------------------------------------------------------------------
# flops profiler
# ---------------------------------------------------------------------------------
def test_flops_profiler_matmul():
    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 512), jnp.float32)
    prof = FlopsProfiler(lambda a, b: a @ b).compile(a, b)
    expected = 2 * 128 * 256 * 512
    assert prof.flops == pytest.approx(expected, rel=0.1)
    stats = prof.measure(a, b, n_iters=3)
    assert stats["latency_s"] > 0 and stats["flops_per_s"] > 0


def test_transformer_flops_formula():
    from deepspeed_tpu.models import TransformerConfig

    cfg = TransformerConfig(vocab_size=1000, n_layers=2, n_heads=4, d_model=64,
                            d_ff=256, max_seq_len=128)
    f_fwd_only = transformer_train_flops(cfg, 4, 128, include_backward=False)
    f_train = transformer_train_flops(cfg, 4, 128)
    f_remat = transformer_train_flops(cfg, 4, 128, checkpoint_activations=True)
    assert f_train == 3 * f_fwd_only
    assert f_remat == 4 * f_fwd_only


def test_csv_monitor_writes_rows(tmp_path):
    from deepspeed_tpu.config import load_config
    from deepspeed_tpu.monitor.monitor import MonitorMaster

    cfg = load_config({
        "train_batch_size": 8,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "job1"},
    })
    m = MonitorMaster(cfg)
    assert m.enabled
    m.write_events([("Train/loss", 1.5, 1), ("Train/loss", 1.25, 2),
                    ("Train/lr", 1e-4, 1)])
    import csv as _csv

    loss_file = tmp_path / "job1" / "Train_loss.csv"
    rows = list(_csv.reader(open(loss_file)))
    assert rows[0] == ["step", "Train/loss"]
    assert rows[1] == ["1", "1.5"] and rows[2] == ["2", "1.25"]
    assert (tmp_path / "job1" / "Train_lr.csv").exists()


def test_tensorboard_monitor_writes_events(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    from deepspeed_tpu.config import load_config
    from deepspeed_tpu.monitor.monitor import TensorBoardMonitor

    cfg = load_config({
        "train_batch_size": 8,
        "tensorboard": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "tbjob"},
    })
    m = TensorBoardMonitor(cfg)
    assert m.enabled
    m.write_events([("Train/loss", 2.0, 1)])
    files = [p for p in (tmp_path).rglob("events.out.tfevents.*")]
    assert files, list(tmp_path.rglob("*"))


def test_engine_writes_monitor_events(tmp_path, devices8):
    """steps_per_print drives loss/lr events through the engine's fused path."""
    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig
    import jax.numpy as jnp

    eng, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(TransformerConfig(
            vocab_size=64, max_seq_len=32, n_layers=2, n_heads=2, d_model=16,
            d_ff=32, compute_dtype=jnp.float32)),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "mesh": {"data": 8},
            "steps_per_print": 2,
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                            "job_name": "engine"},
        })
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 64, (8, 16)).astype(np.int32)}
    for _ in range(4):
        eng.train_batch(batch=batch)
    out = tmp_path / "engine"
    assert (out / "Train_loss.csv").exists()
    assert (out / "Train_lr.csv").exists()


# ---------------------------------------------------------------------------------
# flops profiler per-module breakdown (reference profiler.py:66)
# ---------------------------------------------------------------------------------
def test_module_profile_sums_to_totals():
    from deepspeed_tpu.models import CausalLM, TransformerConfig
    from deepspeed_tpu.profiling import get_module_profile

    cfg = TransformerConfig(vocab_size=64, max_seq_len=32, n_layers=4, n_heads=4,
                            d_model=16, d_ff=32, compute_dtype=jnp.float32)
    batch = {"input_ids": np.zeros((2, 16), np.int32)}
    prof = get_module_profile(CausalLM(cfg), batch, n_iters=2,
                              print_profile=False)
    mods, total = prof["modules"], prof["total"]
    # every top-level module of the tree is present, blocks split by submodule
    for name in ("wte", "wpe", "blocks/attn", "blocks/mlp", "blocks/ln_1",
                 "blocks/ln_2", "ln_f", "lm_head"):
        assert name in mods, name
    # params sum exactly to the real tree's count
    assert sum(m["params"] for m in mods.values()) == total["params"]
    n_leaf_params = 16 * 64 + 32 * 16 + 2 * 16  # wte + wpe + ln_f
    assert total["params"] > n_leaf_params
    # flops and attributed latency sum to the totals row
    np.testing.assert_allclose(sum(m["flops"] for m in mods.values()),
                               total["flops"])
    np.testing.assert_allclose(sum(m["latency_ms"] for m in mods.values()),
                               total["latency_ms"], rtol=1e-6)
    # attention and mlp dominate a transformer's flops
    assert mods["blocks/attn"]["flops"] > 0 and mods["blocks/mlp"]["flops"] > 0
    assert mods["lm_head"]["flops"] > 0
    # the analytic total is within an order of magnitude of XLA's own count
    # (loose sanity band: at tiny shapes the CPU backend's cost analysis
    # diverges from the 2*m*n*k accounting — constant folding, fused
    # elementwise, MAC-vs-flop conventions)
    assert 0.1 < total["flops"] / max(total["xla_flops"], 1.0) < 10.0


def test_module_profile_moe_rows():
    from deepspeed_tpu.models import CausalLM, TransformerConfig
    from deepspeed_tpu.profiling import get_module_profile

    cfg = TransformerConfig(vocab_size=64, max_seq_len=32, n_layers=2, n_heads=2,
                            d_model=16, d_ff=32, compute_dtype=jnp.float32,
                            n_experts=4, moe_top_k=1, moe_use_residual=True)
    prof = get_module_profile(CausalLM(cfg),
                              {"input_ids": np.zeros((2, 16), np.int32)},
                              n_iters=1, print_profile=False)
    assert sum(m["params"] for m in prof["modules"].values()) == \
        prof["total"]["params"]
    # MoE flops count the drop-free eval capacity the profiled forward
    # actually executes, so the analytic total stays near XLA's count
    ratio = prof["total"]["flops"] / max(prof["total"]["xla_flops"], 1.0)
    assert 0.1 < ratio < 10.0, ratio
