"""Pin tools/scale_projection.py's HLO collective accounting.

The parser feeds the v4-256 projection artifact (PERF.md); its two subtle
rules — while-body ops multiplied by the loop trip count, and async
``-start`` ops reading the OUTPUT element of their result tuple — were both
sources of silent 40-256x accounting errors when first written, so they are
pinned here against a hand-built HLO snippet.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools"))

from scale_projection import parse_collectives  # noqa: E402

HLO = """
HloModule test

%wide.body.1 (arg: f32[8]) -> f32[8] {
  %p = f32[8] parameter(0)
  %ag = f32[1024,1024] all-gather(f32[128,1024] %x), dimensions={0}
  ROOT %r = f32[8] add(%p, %p)
}

%cond.1 (arg: f32[8]) -> pred[] {
  %p = f32[8] parameter(0)
  ROOT %c = pred[] constant(true)
}

ENTRY %main (a: f32[128,1024]) -> f32[1024,1024] {
  %a = f32[128,1024] parameter(0)
  %w = f32[8] while(f32[8] %init), condition=%cond.1, body=%wide.body.1
  %ags = (f32[128,1024], f32[1024,1024]) all-gather-start(f32[128,1024] %a), dimensions={0}
  %agd = f32[1024,1024] all-gather-done((f32[128,1024], f32[1024,1024]) %ags)
  %ar = f32[512,64] all-reduce(f32[512,64] %b), to_apply=%sum
  ROOT %out = f32[1024,1024] copy(%agd)
}
"""


def test_body_ops_multiplied_by_trip_count():
    stats = parse_collectives(HLO, n_devices=8, loop_trip_count=24)
    ag = stats["all-gather"]
    # 2 gather ops total: one in the while body (x24), one async in main (x1)
    assert ag["count"] == 2
    full = 1024 * 1024 * 4
    frac = 7 / 8
    expect = full * frac * 24 + full * frac
    assert abs(ag["wire_bytes"] - expect) / expect < 1e-9
    assert ag["by_computation"]["wide.body.1"] == 1
    assert "wide.body.1" in stats["_loop_body_computations"]


def test_async_start_reads_output_tuple_element():
    stats = parse_collectives(HLO, n_devices=8, loop_trip_count=1)
    ag = stats["all-gather"]
    # both ops contribute the FULL gathered result (1024x1024), not the
    # 128x1024 operand — the async start op's first tuple element is the
    # operand and must not be the one counted
    per_op = 1024 * 1024 * 4 * (7 / 8)
    assert abs(ag["wire_bytes"] - 2 * per_op) < 1.0


def test_all_reduce_wire_is_two_passes():
    stats = parse_collectives(HLO, n_devices=8, loop_trip_count=1)
    ar = stats["all-reduce"]
    assert ar["count"] == 1
    expect = 2 * 512 * 64 * 4 * (7 / 8)  # RS + AG passes of a ring
    assert abs(ar["wire_bytes"] - expect) < 1.0
