"""Loss scaler / overflow tests (reference analogue: tests/unit/runtime/half_precision)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops import (
    make_scaler_state,
    check_overflow,
    update_scale,
    scale_loss,
    unscale_grads,
    global_grad_norm,
    clip_grads_by_global_norm,
)


def test_static_vs_dynamic_init():
    s = make_scaler_state(static_scale=128.0)
    assert float(s["scale"]) == 128.0 and not s["_dynamic"]
    d = make_scaler_state(initial_scale_power=8)
    assert float(d["scale"]) == 256.0 and d["_dynamic"]


def test_check_overflow():
    clean = {"a": jnp.ones(4), "b": jnp.zeros(3)}
    assert not bool(check_overflow(clean))
    dirty = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.zeros(3)}
    assert bool(check_overflow(dirty))
    inf = {"a": jnp.array([1.0, jnp.inf])}
    assert bool(check_overflow(inf))


def test_update_scale_dynamics():
    scale = jnp.asarray(1024.0)
    good = jnp.asarray(0)
    # overflow halves
    s1, g1 = update_scale(scale, good, jnp.asarray(True))
    assert float(s1) == 512.0 and int(g1) == 0
    # clean window doubles
    s, g = jnp.asarray(4.0), jnp.asarray(0)
    for _ in range(3):
        s, g = update_scale(s, g, jnp.asarray(False), loss_scale_window=3)
    assert float(s) == 8.0 and int(g) == 0
    # floor at min_scale
    s2, _ = update_scale(jnp.asarray(1.0), good, jnp.asarray(True), min_scale=1.0)
    assert float(s2) == 1.0


def test_scale_unscale_roundtrip():
    grads = {"w": jnp.asarray([2.0, 4.0], jnp.float16)}
    scale = jnp.asarray(1024.0, jnp.float32)
    loss = scale_loss(jnp.asarray(0.5, jnp.float16), scale)
    assert float(loss) == 512.0
    un = unscale_grads({"w": grads["w"] * scale.astype(jnp.float16)}, scale)
    np.testing.assert_allclose(np.asarray(un["w"]), [2.0, 4.0], rtol=1e-3)
    assert un["w"].dtype == jnp.float32


def test_global_norm_and_clip():
    grads = {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])}
    norm = global_grad_norm(grads, eps=0.0)
    assert float(norm) == 5.0
    clipped, norm2 = clip_grads_by_global_norm(grads, max_norm=1.0)
    assert float(norm2) == 5.0
    total = np.sqrt(sum(float(jnp.sum(g ** 2)) for g in clipped.values()))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)
    # under the limit: unchanged
    same, _ = clip_grads_by_global_norm(grads, max_norm=10.0)
    np.testing.assert_allclose(np.asarray(same["a"]), [3.0])
