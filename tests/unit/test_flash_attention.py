"""Flash attention parity vs the reference XLA path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.layers import dot_product_attention, causal_mask
from deepspeed_tpu.ops.flash_attention import _chunked_attention, flash_attention


def _qkv(b=2, s=64, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), dtype)
    return mk(), mk(), mk()


def test_chunked_matches_dense_causal():
    q, k, v = _qkv()
    mask = causal_mask(64, 64)
    dense = dot_product_attention(q, k, v, mask=mask)
    chunked = _chunked_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_chunked_matches_dense_full():
    q, k, v = _qkv(seed=3)
    dense = dot_product_attention(q, k, v, mask=None)
    chunked = _chunked_attention(q, k, v, causal=False, block_size=32)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_chunked_single_block_and_ragged():
    q, k, v = _qkv(s=48, seed=5)
    dense = dot_product_attention(q, k, v, mask=causal_mask(48, 48))
    # 48 % 32 != 0 -> falls back to one chunk
    chunked = _chunked_attention(q, k, v, causal=True, block_size=32)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_cross_attention_kv_longer():
    """Decode-style: q shorter than kv, causal window aligned to the kv end."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 4, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    dense = dot_product_attention(q, k, v, mask=causal_mask(4, 16))
    chunked = _chunked_attention(q, k, v, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_flash_grad_flows():
    q, k, v = _qkv(s=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for t in g:
        assert np.all(np.isfinite(np.asarray(t)))
        assert float(jnp.abs(t).sum()) > 0


def test_bf16_io_dtype():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = _chunked_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
