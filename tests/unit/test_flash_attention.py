"""Flash attention parity vs the reference XLA path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.layers import dot_product_attention, causal_mask
from deepspeed_tpu.ops.flash_attention import _chunked_attention, flash_attention


def _qkv(b=2, s=64, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), dtype)
    return mk(), mk(), mk()


def test_chunked_matches_dense_causal():
    q, k, v = _qkv()
    mask = causal_mask(64, 64)
    dense = dot_product_attention(q, k, v, mask=mask)
    chunked = _chunked_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_chunked_matches_dense_full():
    q, k, v = _qkv(seed=3)
    dense = dot_product_attention(q, k, v, mask=None)
    chunked = _chunked_attention(q, k, v, causal=False, block_size=32)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_chunked_single_block_and_ragged():
    q, k, v = _qkv(s=48, seed=5)
    dense = dot_product_attention(q, k, v, mask=causal_mask(48, 48))
    # 48 % 32 != 0 -> falls back to one chunk
    chunked = _chunked_attention(q, k, v, causal=True, block_size=32)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_cross_attention_kv_longer():
    """Decode-style: q shorter than kv, causal window aligned to the kv end."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 4, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    dense = dot_product_attention(q, k, v, mask=causal_mask(4, 16))
    chunked = _chunked_attention(q, k, v, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(chunked), rtol=2e-5, atol=2e-5)


def test_flash_grad_flows():
    q, k, v = _qkv(s=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for t in g:
        assert np.all(np.isfinite(np.asarray(t)))
        assert float(jnp.abs(t).sum()) > 0


def test_bf16_io_dtype():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = _chunked_attention(q, k, v)
    assert out.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# Pallas kernel parity (interpret mode on CPU; same code path as TPU)
# ---------------------------------------------------------------------------
from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("blocks", [(32, 32), (32, 64), (64, 32)])
def test_pallas_fwd_matches_dense(causal, blocks):
    bq, bkv = blocks
    q, k, v = _qkv(b=1, s=128, h=2, d=32, seed=7)
    mask = causal_mask(128, 128) if causal else None
    dense = dot_product_attention(q, k, v, mask=mask)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=bq,
                                 block_kv=bkv, interpret=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_bwd_matches_dense(causal):
    q, k, v = _qkv(b=1, s=128, h=2, d=32, seed=11)
    mask = causal_mask(128, 128) if causal else None

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, mask=mask) ** 2)

    def loss_pallas(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, causal=causal,
                                              block_q=32, block_kv=64,
                                              interpret=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_pallas_bwd_uneven_blocks():
    """bwd clamps blocks to 256; check a case where q/kv blocks differ."""
    q, k, v = _qkv(b=2, s=64, h=2, d=16, seed=13)

    def loss_pallas(q, k, v):
        return jnp.mean(pallas_flash_attention(q, k, v, causal=True,
                                               block_q=16, block_kv=32,
                                               interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(
            dot_product_attention(q, k, v, mask=causal_mask(64, 64)) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_pallas_decode_q_shorter_than_kv():
    rng = np.random.RandomState(17)
    q = jnp.asarray(rng.randn(1, 32, 2, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    dense = dot_product_attention(q, k, v, mask=causal_mask(32, 128))
    out = pallas_flash_attention(q, k, v, causal=True, block_q=32,
                                 block_kv=32, interpret=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_pallas_bwd_nondivisible_clamp_is_safe():
    """Regression: a valid fwd block (384) used to clamp to 256 in bwd without a
    divisibility check, silently truncating the grid -> NaN gradient rows."""
    q, k, v = _qkv(b=1, s=96, h=1, d=16, seed=19)  # 96 % 64 != 0

    def loss(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, causal=True,
                                              block_q=96, block_kv=96,
                                              interpret=True) ** 2)

    def ref(q, k, v):
        return jnp.sum(
            dot_product_attention(q, k, v, mask=causal_mask(96, 96)) ** 2)

    # force the bwd clamp path: min(96, 256)=96 divides, so emulate by blocks 64
    g_pal = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_pal):
        assert np.all(np.isfinite(np.asarray(b_)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_pallas_lse_named_for_remat_policy():
    """The "minimal" remat policy saves attn_out + attn_lse: the backward must
    NOT re-run the forward flash kernel to regenerate the lse residual (3
    pallas_calls total: fwd + dq + dkv — not 4)."""
    from jax.ad_checkpoint import checkpoint_name

    from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention

    q = jnp.ones((1, 128, 2, 32), jnp.float32)

    def attn(q):
        out = pallas_flash_attention(q, q, q, True, None, 64, 64, True)
        return (checkpoint_name(out, "attn_out") * q).sum()

    def count(names):
        pol = jax.checkpoint_policies.save_only_these_names(*names)
        f = jax.checkpoint(attn, policy=pol)
        return str(jax.make_jaxpr(jax.grad(f))(q)).count("pallas_call")

    assert count(("attn_out", "attn_lse")) == 3
    # sanity: without the lse name the recompute re-runs the fwd kernel
    assert count(("attn_out",)) == 4


def test_jax_flash_cpu_fallback_matches_dense():
    # off-TPU jax_flash_attention routes through _chunked_attention — the
    # dispatch itself (and the [b,s,h,d] signature contract) is what's under
    # test; the TPU branch is exercised by tools/bench_attention.py on chip
    from deepspeed_tpu.ops.flash_attention import jax_flash_attention

    q, k, v = _qkv(seed=11)
    dense = dot_product_attention(q, k, v, mask=causal_mask(64, 64))
    out = jax_flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_jax_flash_model_trains():
    # attention_impl="jax_flash" must thread through the transformer block:
    # fwd + grad on the CPU fallback, loss parity with the xla impl
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    from deepspeed_tpu.models.layers import split_params_axes

    def loss_for(impl):
        cfg = TransformerConfig(
            vocab_size=128, max_seq_len=64, n_layers=2, n_heads=4,
            d_model=64, d_ff=128, attention_impl=impl, dropout=0.0)
        model = CausalLM(cfg)
        params, _ = split_params_axes(model.init(jax.random.PRNGKey(0)))
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 128, (2, 64)), jnp.int32)

        def loss_fn(p):
            return model.loss(p, {"input_ids": ids})

        l, g = jax.value_and_grad(loss_fn)(params)
        return float(l), g

    l_xla, _ = loss_for("xla")
    l_jf, g = loss_for("jax_flash")
    assert abs(l_xla - l_jf) < 1e-3
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))


def test_single_kv_block_path_matches_general():
    """The specialized no-scratch kernel (n_kvb == 1 — the measured-winner
    tile configuration) must match the general online-softmax kernel
    bitwise-closely, fwd AND bwd, causal and not, including the lse
    residual used under remat."""
    from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention

    rng = np.random.RandomState(3)
    b, s, h, d = 2, 256, 4, 64
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    # (bq, bkv, bq_bwd, bkv_bwd): each single-block specialization alone,
    # then all at once (the maxq shape)
    variants = [
        (128, s, 128, 128),   # fwd single-kv-block
        (128, 128, 128, s),   # dq single-kv-block
        (128, 128, s, 128),   # dkv single-q-block
        (s, s, s, s),         # everything single (maxq)
    ]
    for causal in (True, False):
        def loss(blocks):
            bq_, bkv_, bqb, bkvb = blocks
            def f(q, k, v):
                return pallas_flash_attention(
                    q, k, v, causal=causal, block_q=bq_, block_kv=bkv_,
                    block_q_bwd=bqb, block_kv_bwd=bkvb, interpret=True).sum()
            return f

        o2, g2 = jax.value_and_grad(
            loss((128, 128, 128, 128)), argnums=(0, 1, 2))(q, k, v)
        for blocks in variants:
            o1, g1 = jax.value_and_grad(
                loss(blocks), argnums=(0, 1, 2))(q, k, v)
            np.testing.assert_allclose(float(o1), float(o2), rtol=2e-5,
                                       err_msg=str(blocks))
            for a, b_ in zip(g1, g2):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                           rtol=2e-4, atol=2e-5,
                                           err_msg=str(blocks))


def test_single_block_paths_with_kv_longer_than_q():
    """q_offset != 0 through every specialized kernel: non-causal uses
    s_q < s_kv directly; causal requires s_q <= s_kv and exercises the
    '+ q_offset' term of the single-block masks (a sign error there passes
    all square-shape tests silently)."""
    from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention

    rng = np.random.RandomState(5)
    b, s_q, s_kv, h, d = 2, 128, 256, 2, 64
    q = jnp.asarray(rng.standard_normal((b, s_q, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s_kv, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s_kv, h, d)), jnp.float32)

    for causal in (True, False):
        def loss(blocks):
            bq_, bkv_, bqb, bkvb = blocks
            def f(q, k, v):
                return pallas_flash_attention(
                    q, k, v, causal=causal, block_q=bq_, block_kv=bkv_,
                    block_q_bwd=bqb, block_kv_bwd=bkvb, interpret=True).sum()
            return f

        o2, g2 = jax.value_and_grad(
            loss((64, 64, 64, 64)), argnums=(0, 1, 2))(q, k, v)
        for blocks in [(64, s_kv, 64, 64),   # fwd single-kv-block
                       (64, 64, 64, s_kv),   # dq single-kv-block
                       (64, 64, s_q, 64),    # dkv single-q-block
                       (s_q, s_kv, s_q, s_kv)]:
            o1, g1 = jax.value_and_grad(
                loss(blocks), argnums=(0, 1, 2))(q, k, v)
            np.testing.assert_allclose(float(o1), float(o2), rtol=2e-5,
                                       err_msg=f"causal={causal} {blocks}")
            for a, b_ in zip(g1, g2):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                           rtol=2e-4, atol=2e-5,
                                           err_msg=f"causal={causal} {blocks}")
