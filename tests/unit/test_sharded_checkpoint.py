"""Sharded checkpoint: per-shard save, universal reshape-on-load, consolidation.

Reference capability: ``deepspeed/checkpoint/universal_checkpoint.py`` +
``reshape_meg_2d.py`` — checkpoints survive dp/tp/pp resizes; ``zero_to_fp32``
consolidation; no full-model gather on save.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.checkpoint.engine import NpzCheckpointEngine
from deepspeed_tpu.checkpoint.sharded import ShardedCheckpointEngine, consolidate
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.models import get_model
from deepspeed_tpu.parallel import build_mesh


def _tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _mk_state(mesh, spec):
    x = jnp.arange(64 * 48, dtype=jnp.float32).reshape(64, 48)
    return {"w": jax.device_put(x, NamedSharding(mesh, spec)),
            "scalar": jnp.asarray(3, jnp.int32)}


def test_save_load_same_sharding(tmp_path, devices8):
    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    state = _mk_state(mesh, P("data", None))
    eng = ShardedCheckpointEngine()
    eng.save(state, str(tmp_path / "t"), meta={"step": 7})
    out, meta = eng.load(str(tmp_path / "t"), template=state,
                         shardings={"w": NamedSharding(mesh, P("data", None)),
                                    "scalar": NamedSharding(mesh, P())})
    assert meta["step"] == 7
    _tree_equal(state, out)


@pytest.mark.parametrize("src,dst", [
    (P("data", None), P(None, "data")),
    (P("data", "model"), P("model", None)),
    (P(), P("data", "model")),
])
def test_reshape_across_specs(tmp_path, devices8, src, dst):
    """Save under one layout, load under another — the universal reshape."""
    mesh = build_mesh(MeshConfig(data=4, model=2), devices=devices8)
    state = _mk_state(mesh, src)
    eng = ShardedCheckpointEngine()
    eng.save(state, str(tmp_path / "t"))
    out, _ = eng.load(str(tmp_path / "t"), template=state,
                      shardings={"w": NamedSharding(mesh, dst),
                                 "scalar": NamedSharding(mesh, P())})
    _tree_equal(state, out)
    assert out["w"].sharding.spec == dst


def test_no_replica_duplication(tmp_path, devices8):
    """Replicated leaves are written once, not once per device."""
    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    state = {"w": jax.device_put(jnp.ones((16, 16)), NamedSharding(mesh, P()))}
    ShardedCheckpointEngine().save(state, str(tmp_path / "t"))
    blobs = np.load(str(tmp_path / "t" / "shards-0.npz"))
    assert len(blobs.files) == 1


def test_legacy_npz_fallback(tmp_path, devices8):
    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    state = _mk_state(mesh, P("data", None))
    NpzCheckpointEngine().save(state, str(tmp_path / "t"))
    out, _ = ShardedCheckpointEngine().load(
        str(tmp_path / "t"), template=state,
        shardings={"w": NamedSharding(mesh, P("data", None)),
                   "scalar": NamedSharding(mesh, P())})
    _tree_equal(state, out)


def test_consolidate(tmp_path, devices8):
    mesh = build_mesh(MeshConfig(data=4, model=2), devices=devices8)
    state = _mk_state(mesh, P("data", "model"))
    ShardedCheckpointEngine().save(state, str(tmp_path / "t"))
    out_dir = consolidate(str(tmp_path / "t"))
    arrays = np.load(os.path.join(out_dir, "arrays.npz"))
    np.testing.assert_array_equal(arrays["w"], np.asarray(state["w"]))
    # the export is durable (committed, checksummed) but a side ARTIFACT:
    # never a resume candidate, never counted/pruned by retention
    from deepspeed_tpu.checkpoint import atomic
    marker = atomic.read_marker(out_dir)
    assert marker["kind"] == "artifact" and marker["arrays"]
    assert atomic.list_tags(str(tmp_path)) == ["t"]
    assert atomic.resume_candidates(str(tmp_path)) == ["t"]


def test_incomplete_checkpoint_raises(tmp_path, devices8):
    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    state = _mk_state(mesh, P("data", None))
    eng = ShardedCheckpointEngine()
    eng.save(state, str(tmp_path / "t"))
    # corrupt: claim a piece exists but drop it from the blob file — caught
    # either by COMMITTED-marker verification or by piece-coverage assembly
    pieces = json.load(open(tmp_path / "t" / "pieces-0.json"))
    pieces["w"] = dict(list(pieces["w"].items())[:1])  # forget the rest of the leaf
    json.dump(pieces, open(tmp_path / "t" / "pieces-0.json", "w"))
    with pytest.raises(ValueError, match="do not cover|failed verification"):
        eng.load(str(tmp_path / "t"), template=state,
                 shardings={"w": NamedSharding(mesh, P()),
                            "scalar": NamedSharding(mesh, P())})


def test_engine_roundtrip_across_mesh_change(tmp_path, devices8):
    """Train on dp=8 ZeRO-3, save; rebuild on dp=2 x tp=4, load; same loss —
    the reference needs universal-checkpoint reshape tooling for this."""
    rngnp = np.random.RandomState(0)
    batch = {"input_ids": rngnp.randint(0, 1024, (8, 32)).astype(np.int32)}

    def mk(meshcfg, zero):
        model = get_model("llama", "tiny", compute_dtype=jnp.float32)
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero}, "mesh": meshcfg,
            "steps_per_print": 10 ** 9})
        return eng

    e1 = mk({"data": 8}, 3)
    loss = e1.forward(batch)
    e1.backward(loss)
    e1.step()
    e1.save_checkpoint(str(tmp_path), tag="t")

    e2 = mk({"data": 2, "model": 4}, 1)
    e2.load_checkpoint(str(tmp_path), tag="t")
    l1 = float(e1.eval_batch(batch))
    l2 = float(e2.eval_batch(batch))
    assert abs(l1 - l2) < 1e-4, (l1, l2)


def test_engine_roundtrip_across_pipe_resize(tmp_path, devices8):
    """3D reshape: save on pipe=2 x dp=4 (layer stack sharded over pipe),
    load on dp=8 — and back. The reference's reshape_3d_utils territory."""
    rngnp = np.random.RandomState(1)
    batch = {"input_ids": rngnp.randint(0, 1024, (8, 32)).astype(np.int32)}

    def mk(meshcfg, gas):
        model = get_model("llama", "tiny", compute_dtype=jnp.float32)
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 1 if gas > 1 else None,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1}, "mesh": meshcfg,
            "steps_per_print": 10 ** 9})
        return eng

    e1 = mk({"data": 4, "pipe": 2}, 2)
    e1.train_batch(batch=batch)
    e1.save_checkpoint(str(tmp_path), tag="p")

    e2 = mk({"data": 8}, 1)
    e2.load_checkpoint(str(tmp_path), tag="p")
    l1 = float(e1.eval_batch(batch))
    l2 = float(e2.eval_batch(batch))
    assert abs(l1 - l2) < 1e-4, (l1, l2)

    # and back onto a pipe mesh
    e2.save_checkpoint(str(tmp_path), tag="q")
    e3 = mk({"data": 4, "pipe": 2}, 2)
    e3.load_checkpoint(str(tmp_path), tag="q")
    l3 = float(e3.eval_batch(batch))
    assert abs(l2 - l3) < 1e-4, (l2, l3)


def test_async_engine_roundtrip_and_error_surfacing(tmp_path, devices8):
    """Async sharded engine (the Nebula-engine durability contract): commit
    joins the writer and re-raises background failures; a good save round
    trips exactly."""
    from deepspeed_tpu.checkpoint.sharded import AsyncShardedCheckpointEngine

    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    state = _mk_state(mesh, P("data", None))
    eng = AsyncShardedCheckpointEngine()
    eng.save(state, str(tmp_path / "ok"), meta={"step": 3})
    assert eng.commit("t")
    out, meta = eng.load(str(tmp_path / "ok"), template=state,
                         shardings={"w": NamedSharding(mesh, P("data", None)),
                                    "scalar": NamedSharding(mesh, P())})
    assert meta["step"] == 3
    _tree_equal(state, out)

    # unwritable destination: the failure surfaces at commit, not silently
    blocked = tmp_path / "blocked"
    blocked.write_text("a file where a directory must go")
    eng2 = AsyncShardedCheckpointEngine()
    eng2.save(state, str(blocked / "ckpt"))
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        eng2.commit("t")
