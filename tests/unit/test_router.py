"""Router tier + chunked prefill + on-demand growth tests (tier-1).

The acceptance invariants of the millions-of-users serving topology
(ROADMAP item 2), all assertable under the virtual clock:

- greedy token streams THROUGH THE ROUTER (N>=2 replicas, chunked prefill
  on, paged pool with on-demand growth) are bitwise-equal to sequential
  single-replica ``generate()``, single-device and TP=2;
- least-loaded dispatch strictly beats round-robin (makespan) on skewed
  arrivals; prefix-affinity routing shows a strictly higher aggregate
  prefix hit rate than round-robin on repeated-system-prompt workloads;
- drain/rejoin completes every in-flight request with zero sheds;
- the chunked-prefill TPOT ceiling holds for a co-batched decoder while a
  max-length prompt prefills (vs an unbounded stall without chunking);
- on-demand growth admits strictly more concurrent requests than
  whole-footprint reservation at byte-identical pool sizes, preempting to
  the queue instead of OOM/shed on exhaustion — and a preempted request
  resumes bitwise-identically (greedy AND seeded sampling);
- FCFS head-of-line bypass admits a later fitting request past a blocked
  head only within the configured starvation window;
- Serving/router_* monitor events stay coherent with
  ``ServingMetrics.snapshot()["router"]`` (the PR 4 trace==metrics pin).
"""

import numpy as np
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import ServingConfig
from deepspeed_tpu.models import CausalLM, TransformerConfig, split_params_axes
from deepspeed_tpu.serving import (Request, RequestState, Router,
                                   SamplingParams, ServingEngine,
                                   VirtualClock)


def tiny_cfg(**kw):
    base = dict(vocab_size=64, max_seq_len=64, n_layers=2, n_heads=4,
                d_model=16, d_ff=32, compute_dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def engine():
    """One tiny fp32 engine shared by the module (weights + generate cache);
    each test builds its own ServingEngine replicas over it."""
    model = CausalLM(tiny_cfg())
    return deepspeed_tpu.init_inference(
        model, dtype="float32", max_tokens=64, prompt_bucket_size=16)


def make_replica(engine, **kw):
    kw.setdefault("virtual_clock", True)
    kw.setdefault("n_slots", 2)
    return ServingEngine(engine, serving_config=ServingConfig(**kw),
                         clock=VirtualClock())


def make_router(engine, n=2, router=None, **kw):
    replicas = [make_replica(engine, **kw) for _ in range(n)]
    cfg = replicas[0].cfg.router
    if router:
        cfg = cfg.replace(**router)
    return Router(replicas, config=cfg)


def ref_tokens(engine, req):
    out = np.asarray(engine.generate(req.prompt[None, :],
                                     max_new_tokens=req.max_new_tokens,
                                     greedy=True))
    return out[0, req.prompt_len:]


# ---------------------------------------------------------------------------
# 1. bitwise parity through the full topology
# ---------------------------------------------------------------------------

def test_router_greedy_parity_chunked_paged_growth(engine):
    """The acceptance pin: greedy streams through the router — 2 replicas,
    chunked prefill ON, paged pool with on-demand growth ON — are bitwise
    equal to sequential single-replica generate(). Chunking, routing, growth
    and preemption change the SCHEDULE, never the math."""
    rng = np.random.RandomState(0)
    router = make_router(
        engine, n=2,
        chunked_prefill={"enabled": True, "chunk_size": 8},
        kv_pool={"enabled": True, "block_size": 8, "on_demand_growth": True})
    reqs = [Request(
        prompt=rng.randint(0, 64, (int(rng.randint(4, 40)),)).astype(np.int32),
        max_new_tokens=int(rng.randint(3, 9)), arrival_time=i * 0.5)
        for i in range(8)]
    finished, rejected, snap = router.run(reqs)
    assert len(finished) == 8 and not rejected
    for r in reqs:
        np.testing.assert_array_equal(np.asarray(r.tokens), ref_tokens(engine, r))
    # both replicas actually served work, each compiling decode exactly once
    assert all(n > 0 for n in snap["router"]["per_replica_routed"])
    assert all(c["decode"] == 1 and c["insert"] == 1
               for c in router.compile_counts())


def test_router_tp_mesh_parity(devices8):
    """TP=2 fleet: two replicas over a model-sharded engine, chunked prefill
    + paged growth on — greedy streams still match the single-device
    reference bitwise (the acceptance pin's TP leg)."""
    import jax

    from deepspeed_tpu.config import MeshConfig
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.parallel import build_mesh

    cfg = tiny_cfg(position_embedding="rope")
    model = CausalLM(cfg)
    values, _ = split_params_axes(model.init(jax.random.PRNGKey(4)))
    mesh = build_mesh(MeshConfig(model=2, data=4), devices=devices8)
    eng = InferenceEngine(model, DeepSpeedInferenceConfig.from_dict(
        {"dtype": "float32", "max_tokens": 64,
         "tensor_parallel": {"tp_size": 2},
         "serving": {"n_slots": 2, "virtual_clock": True,
                     "chunked_prefill": {"enabled": True, "chunk_size": 8},
                     "kv_pool": {"enabled": True, "block_size": 8,
                                 "on_demand_growth": True}}}), mesh=mesh)
    eng.params = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, s), values, eng.param_shardings)

    rng = np.random.RandomState(9)
    router = Router([ServingEngine(eng, clock=VirtualClock())
                     for _ in range(2)])
    reqs = [Request(
        prompt=rng.randint(0, 64, (int(rng.randint(4, 30)),)).astype(np.int32),
        max_new_tokens=int(rng.randint(3, 7)), arrival_time=i * 0.5)
        for i in range(4)]
    finished, rejected, _ = router.run(reqs)
    assert len(finished) == 4 and not rejected

    raw = deepspeed_tpu.init_inference(CausalLM(cfg), dtype="float32",
                                       max_tokens=64)
    raw.params = values
    for r in reqs:
        ref = np.asarray(raw.generate(
            r.prompt[None, :], max_new_tokens=r.max_new_tokens, greedy=True))
        np.testing.assert_array_equal(np.asarray(r.tokens),
                                      ref[0, r.prompt_len:])
    eng.destroy()


# ---------------------------------------------------------------------------
# 2. routing policy under the virtual clock
# ---------------------------------------------------------------------------

def _skewed_workload(rng):
    """Long/short mix whose arrival order makes round-robin queue a long
    request behind another long one while the other replica sits idle."""
    long_p = rng.randint(0, 64, (8,)).astype(np.int32)
    short_p = rng.randint(0, 64, (8,)).astype(np.int32)
    return [
        Request(prompt=long_p.copy(), max_new_tokens=24, arrival_time=0.0),
        Request(prompt=short_p.copy(), max_new_tokens=3, arrival_time=0.1),
        Request(prompt=long_p.copy(), max_new_tokens=24, arrival_time=6.0),
        Request(prompt=short_p.copy(), max_new_tokens=3, arrival_time=6.1),
    ]


def test_least_loaded_beats_round_robin_on_skewed_arrivals(engine):
    """Deterministic makespan pin: round-robin sends the second long request
    to the replica still busy with the first (the other is idle); the
    least-loaded scorer sends it to the idle one. Same work, strictly
    smaller fleet makespan."""
    rng = np.random.RandomState(1)
    rr = make_router(engine, n=2, n_slots=1, router={"policy": "round_robin"})
    finished, rejected, rr_snap = rr.run(_skewed_workload(rng))
    assert len(finished) == 4 and not rejected

    ll = make_router(engine, n=2, n_slots=1,
                     router={"policy": "least_loaded"})
    finished, rejected, ll_snap = ll.run(_skewed_workload(rng))
    assert len(finished) == 4 and not rejected

    assert ll_snap["makespan"] < rr_snap["makespan"]
    # and the queues tell the story: round-robin queued work behind a busy
    # replica (depth observed > 0 on one side while the other idled)
    assert ll_snap["ttft_ms"]["p99"] < rr_snap["ttft_ms"]["p99"]


def test_prefix_affinity_beats_round_robin_hit_rate(engine):
    """Repeated system prompts: with prefix affinity the router keeps
    sending them to the replica already holding their blocks — the
    aggregate KV prefix hit rate is strictly higher than round-robin's
    (which spreads the same prompt over every replica's pool)."""
    def requests(seed):
        r = np.random.RandomState(seed)
        sys_prompt = r.randint(0, 64, (16,)).astype(np.int32)
        return [Request(
            prompt=np.concatenate(
                [sys_prompt, r.randint(0, 64, (6,)).astype(np.int32)]),
            max_new_tokens=4, arrival_time=i * 3.0) for i in range(6)]

    affin = make_router(engine, n=2,
                        kv_pool={"enabled": True, "block_size": 8})
    _, _, affin_snap = affin.run(requests(2))

    rr = make_router(engine, n=2, router={"policy": "round_robin"},
                     kv_pool={"enabled": True, "block_size": 8})
    _, _, rr_snap = rr.run(requests(2))

    def hit_rate(snap):
        hits = sum(r["kv_pool"]["prefix_hit_requests"]
                   for r in snap["replicas"])
        cands = sum(r["kv_pool"]["prefix_requests"]
                    for r in snap["replicas"])
        return hits / max(cands, 1)

    assert hit_rate(affin_snap) > hit_rate(rr_snap)
    assert affin_snap["router"]["affinity_hit_rate"] > 0
    # round-robin never consults the prefix index
    assert rr_snap["router"]["prefix_hits"] == 0


def test_rebalance_overrides_overloaded_affinity_target(engine):
    """An affinity target drowning in queue depth is overridden (counted as
    a rebalance) instead of piling more work onto it."""
    rng = np.random.RandomState(3)
    router = make_router(engine, n=2, n_slots=1, max_queue_depth=64,
                         router={"rebalance_margin": 0.05},
                         kv_pool={"enabled": True, "block_size": 8})
    sys_prompt = rng.randint(0, 64, (16,)).astype(np.int32)
    mk = lambda t: Request(
        prompt=np.concatenate([sys_prompt,
                               rng.randint(0, 64, (6,)).astype(np.int32)]),
        max_new_tokens=8, arrival_time=t)
    # a burst that all wants replica 0 (prefix affinity) — load wins instead
    _, _, snap = router.run([mk(0.0), mk(0.1), mk(0.2), mk(0.3)])
    assert snap["router"]["rebalances"] > 0
    assert all(n > 0 for n in snap["router"]["per_replica_routed"])


# ---------------------------------------------------------------------------
# 3. drain / rejoin
# ---------------------------------------------------------------------------

def test_drain_rejoin_loses_zero_in_flight(engine):
    """Drain mid-flight: the draining replica takes no NEW work but finishes
    everything it owns (zero sheds); rejoin re-registers it for admissions
    — the PR 11 quiesce-then-teardown discipline at the router tier."""
    rng = np.random.RandomState(4)
    router = make_router(engine, n=2, n_slots=1)
    mk = lambda: Request(prompt=rng.randint(0, 64, (6,)).astype(np.int32),
                         max_new_tokens=8)
    a, b = router.submit(mk()), router.submit(mk())
    assert {a.state, b.state} <= {RequestState.QUEUED, RequestState.RUNNING}
    router.drain(0)
    # new work while draining routes AWAY from replica 0
    c, d = router.submit(mk()), router.submit(mk())
    while any(rep.busy for rep in router._replicas):
        router.step()
    for r in (a, b, c, d):
        assert r.state is RequestState.FINISHED
        np.testing.assert_array_equal(np.asarray(r.tokens),
                                      ref_tokens(engine, r))
    assert router.drained(0)
    snap = router.snapshot()
    assert snap["router"]["drains"] == 1
    assert snap["router"]["shed_all_replicas_saturated"] == 0
    assert sum(sum(r["shed"].values()) for r in snap["replicas"]) == 0
    # while draining, replica 0 received at most its pre-drain share
    routed_while_draining = snap["router"]["per_replica_routed"]
    assert routed_while_draining[1] >= 2

    router.rejoin(0)
    e = router.submit(mk())
    while any(rep.busy for rep in router._replicas):
        router.step()
    assert e.state is RequestState.FINISHED
    assert router.snapshot()["router"]["rejoins"] == 1


def test_all_replicas_saturated_shed(engine):
    """Every replica at queue capacity (or draining) -> the router sheds
    with the cross-replica reason instead of dumping onto one queue."""
    rng = np.random.RandomState(5)
    router = make_router(engine, n=2, n_slots=1, max_queue_depth=1)
    mk = lambda: Request(prompt=rng.randint(0, 64, (5,)).astype(np.int32),
                         max_new_tokens=4)
    reqs = [router.submit(mk()) for _ in range(6)]
    shed = [r for r in reqs if r.state is RequestState.REJECTED]
    assert shed and all(r.reject_reason == "all_replicas_saturated"
                        for r in shed)
    assert router.metrics.shed_saturated == len(shed)
    while any(rep.busy for rep in router._replicas):
        router.step()
    done = [r for r in reqs if r.state is RequestState.FINISHED]
    assert len(done) + len(shed) == 6


# ---------------------------------------------------------------------------
# 4. chunked prefill: the bounded-TPOT guarantee
# ---------------------------------------------------------------------------

def _max_token_gap(events, request_id):
    times = [ev.time for ev in events if ev.request_id == request_id]
    return max(b - a for a, b in zip(times, times[1:]))


def test_chunked_prefill_bounds_cobatched_tpot(engine):
    """A max-length prompt prefills while a decoder streams: with chunked
    prefill the decoder's worst inter-token gap stays under the virtual-
    clock ceiling (chunk bucket * prefill cost + decode cost); without it,
    the whole-prompt prefill stalls the decoder past that ceiling."""
    rng = np.random.RandomState(6)
    dec_prompt = rng.randint(0, 64, (8,)).astype(np.int32)
    big_prompt = rng.randint(0, 64, (56,)).astype(np.int32)
    decoder = lambda: Request(prompt=dec_prompt.copy(), max_new_tokens=20,
                              arrival_time=0.0)
    # max-length prompt: 56 tokens prompt + 8 new fills the 64 window
    big = lambda: Request(prompt=big_prompt.copy(), max_new_tokens=4,
                          arrival_time=3.0)

    chunked = make_replica(
        engine, n_slots=2,
        chunked_prefill={"enabled": True, "chunk_size": 16,
                         "decode_steps_between_chunks": 1})
    d1, b1 = decoder(), big()
    ev_chunked = list(chunked.serve([d1, b1]))
    # ceiling: one 16-token chunk (0.0625/token) + one decode step
    ceiling = 16 * chunked.cfg.virtual_prefill_cost_per_token \
        + chunked.cfg.virtual_decode_step_cost
    gap_chunked = _max_token_gap(ev_chunked, d1.request_id)
    assert gap_chunked <= ceiling + 1e-9, (gap_chunked, ceiling)

    plain = make_replica(engine, n_slots=2)
    d2, b2 = decoder(), big()
    ev_plain = list(plain.serve([d2, b2]))
    gap_plain = _max_token_gap(ev_plain, d2.request_id)
    # the unbounded stall: the whole 56-token prompt (bucketed to 64)
    # lands between two of the decoder's tokens
    assert gap_plain > ceiling
    assert gap_plain >= 56 * plain.cfg.virtual_prefill_cost_per_token

    # chunking changed the schedule, not the tokens
    np.testing.assert_array_equal(np.asarray(d1.tokens), np.asarray(d2.tokens))
    np.testing.assert_array_equal(np.asarray(b1.tokens), np.asarray(b2.tokens))
    np.testing.assert_array_equal(np.asarray(d1.tokens),
                                  ref_tokens(engine, d1))
    np.testing.assert_array_equal(np.asarray(b1.tokens),
                                  ref_tokens(engine, b1))
    # all full chunks share ONE compiled suffix program
    assert chunked.compile_counts()["suffix_buckets"] <= 2


# ---------------------------------------------------------------------------
# 5. on-demand growth: capacity win + preempt/resume
# ---------------------------------------------------------------------------

def test_growth_admits_more_than_whole_footprint(engine):
    """Byte-identical pools: whole-footprint reservation pays for every
    not-yet-generated token at admission; reserve-as-you-decode admits
    strictly more concurrent requests (active_slots_peak), shedding nothing
    and preempting to the queue when the pool saturates mid-decode."""
    rng = np.random.RandomState(7)
    mk_reqs = lambda: [Request(prompt=rng.randint(0, 64, (8,)).astype(np.int32),
                               max_new_tokens=24, arrival_time=0.0)
                       for _ in range(6)]
    pool = {"enabled": True, "block_size": 8, "n_blocks": 9,
            "prefix_cache": False}

    whole = make_replica(engine, n_slots=6, kv_pool=dict(pool))
    rng = np.random.RandomState(7)
    reqs_w = mk_reqs()
    list(whole.serve(reqs_w))
    snap_w = whole.metrics.snapshot()

    grow = make_replica(engine, n_slots=6,
                        kv_pool=dict(pool, on_demand_growth=True))
    rng = np.random.RandomState(7)
    reqs_g = mk_reqs()
    list(grow.serve(reqs_g))
    snap_g = grow.metrics.snapshot()

    # same pool bytes, strictly more concurrency
    assert snap_g["active_slots_peak"] > snap_w["active_slots_peak"]
    assert snap_g["kv_pool"]["grown_blocks"] > 0
    # exhaustion preempted instead of shedding/OOM
    assert snap_g["preempted"] > 0
    assert sum(snap_g["shed"].values()) == 0
    for a, b in zip(reqs_w, reqs_g):
        assert a.state is RequestState.FINISHED
        assert b.state is RequestState.FINISHED
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))


def test_preempted_request_resumes_bitwise_identical(engine):
    """The preempt-to-queue round trip replays prompt + generated tokens
    into fresh blocks and re-enters decode at the saved cursor AND rng —
    greedy streams match generate() and a seeded SAMPLED stream matches its
    un-preempted self token for token."""
    rng = np.random.RandomState(8)
    tight = {"enabled": True, "block_size": 8, "n_blocks": 8,
             "prefix_cache": False, "on_demand_growth": True}
    sampled = lambda: Request(
        prompt=rng.randint(0, 64, (8,)).astype(np.int32), max_new_tokens=20,
        sampling=SamplingParams(temperature=0.8, top_k=8, seed=13),
        arrival_time=0.0)
    greedy = lambda: Request(
        prompt=rng.randint(0, 64, (8,)).astype(np.int32), max_new_tokens=20,
        arrival_time=0.0)

    rng = np.random.RandomState(8)
    sv = make_replica(engine, n_slots=3, kv_pool=dict(tight))
    s1, g1, g2 = sampled(), greedy(), greedy()
    list(sv.serve([s1, g1, g2]))
    assert sv.metrics.snapshot()["preempted"] > 0
    assert max(r.preemptions for r in (s1, g1, g2)) > 0
    # resume replays splice through the SAME compiled insert/decode programs
    counts = sv.compile_counts()
    assert counts["decode"] == 1 and counts["insert"] == 1

    # greedy legs: bitwise vs generate() regardless of preemption
    for g in (g1, g2):
        np.testing.assert_array_equal(np.asarray(g.tokens),
                                      ref_tokens(engine, g))
    # sampled leg: identical to the same seeded request served un-preempted
    rng = np.random.RandomState(8)
    roomy = make_replica(engine, n_slots=3,
                         kv_pool={"enabled": True, "block_size": 8,
                                  "prefix_cache": False})
    s2 = sampled()
    list(roomy.serve([s2]))
    assert s2.preemptions == 0
    assert s1.tokens == s2.tokens


# ---------------------------------------------------------------------------
# 6. FCFS head-of-line bypass (bounded starvation)
# ---------------------------------------------------------------------------

def _hol_setup(engine, bypass):
    """1 running 2-block request + a 4-block head that can't fit + small
    requests behind it that could."""
    sv = make_replica(engine, n_slots=3, hol_bypass_limit=bypass,
                      kv_pool={"enabled": True, "block_size": 8,
                               "n_blocks": 5, "prefix_cache": False})
    rng = np.random.RandomState(9)
    running = Request(prompt=rng.randint(0, 64, (8,)).astype(np.int32),
                      max_new_tokens=9)    # 2 blocks, 9 decode steps
    big = Request(prompt=rng.randint(0, 64, (16,)).astype(np.int32),
                  max_new_tokens=17)       # 4 blocks: can't fit while running
    small = Request(prompt=rng.randint(0, 64, (4,)).astype(np.int32),
                    max_new_tokens=4)      # 1 block: fits beside running
    small2 = Request(prompt=rng.randint(0, 64, (4,)).astype(np.int32),
                     max_new_tokens=4)
    for r in (running, big, small, small2):
        sv.submit(r)
    for _ in range(200):
        sv.step()
        if all(r.state is RequestState.FINISHED
               for r in (running, big, small, small2)):
            break
    return sv, running, big, small, small2


def test_hol_bypass_off_preserves_strict_fcfs(engine):
    sv, running, big, small, small2 = _hol_setup(engine, bypass=0)
    # strict FCFS: the small requests waited behind the blocked big head
    assert small.first_token_time > big.first_token_time
    assert small2.first_token_time > big.first_token_time
    assert sv.pool_mgr.stats()["reserved_blocks"] == 0


def test_hol_bypass_admits_fitting_request_within_window(engine):
    sv, running, big, small, small2 = _hol_setup(engine, bypass=1)
    # one bypass granted: small overtakes the stuck head...
    assert small.first_token_time < big.first_token_time
    # ...but the window is bounded: small2 (bypass #2) must wait for big
    assert small2.first_token_time > big.first_token_time
    # reservation counter consistent after the dust settles
    assert sv.pool_mgr.stats()["reserved_blocks"] == 0
    for r in (running, big, small, small2):
        np.testing.assert_array_equal(np.asarray(r.tokens),
                                      ref_tokens(engine, r))


# ---------------------------------------------------------------------------
# 7. router monitor events == snapshot (trace==metrics discipline)
# ---------------------------------------------------------------------------

def test_router_monitor_events_match_snapshot(engine, tmp_path):
    """Serving/router_* scalars through the CSV monitor backend carry
    exactly the numbers ``snapshot()['router']`` reports — and each
    replica's ServingMetrics.snapshot() exposes the same router block."""
    from deepspeed_tpu.monitor.monitor import MonitorMaster

    mcfg = engine.config.replace(
        csv_monitor={"enabled": True, "output_path": str(tmp_path),
                     "job_name": "router_test"})
    replicas = [make_replica(engine,
                             kv_pool={"enabled": True, "block_size": 8})
                for _ in range(2)]
    router = Router(replicas, monitor=MonitorMaster(mcfg))
    rng = np.random.RandomState(10)
    sys_prompt = rng.randint(0, 64, (16,)).astype(np.int32)
    reqs = [Request(prompt=np.concatenate(
        [sys_prompt, rng.randint(0, 64, (5,)).astype(np.int32)]),
        max_new_tokens=3, arrival_time=i * 2.0) for i in range(5)]
    finished, rejected, snap = router.run(reqs)
    assert len(finished) == 5 and not rejected
    router.metrics.emit_events()

    outdir = tmp_path / "router_test"
    names = {p.name for p in outdir.iterdir()}
    for expected in ("Serving_router_routed.csv",
                     "Serving_router_affinity_hit_rate.csv",
                     "Serving_router_rebalances.csv",
                     "Serving_router_drains.csv",
                     "Serving_router_r0_queue_depth.csv",
                     "Serving_router_r1_occupancy.csv"):
        assert expected in names, names

    def last_value(name):
        rows = (outdir / name).read_text().strip().splitlines()
        return float(rows[-1].split(",")[-1])

    r = snap["router"]
    assert last_value("Serving_router_routed.csv") == float(r["routed"])
    assert last_value("Serving_router_affinity_hit_rate.csv") == \
        pytest.approx(r["affinity_hit_rate"])
    assert last_value("Serving_router_rebalances.csv") == \
        float(r["rebalances"])
    assert last_value("Serving_router_drains.csv") == float(r["drains"])
    # per-replica snapshot coherence: the same router block, same numbers
    for rep in replicas:
        assert rep.metrics.snapshot()["router"]["routed"] == r["routed"]
