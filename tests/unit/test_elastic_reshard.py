"""Resume-at-any-scale + trajectory continuity (ROADMAP item 5 acceptance).

The chaos contract: a seeded SIGTERM at an arbitrary step loses at most the
snapshot cadence and resumes with a BITWISE-identical trajectory at equal
scale (loss-scale, rng stream, and skipped-step counters included); a resume
onto a different mesh (8 -> 4x2 -> 8) reshards params AND ZeRO optimizer
state automatically from the universal sharded layout and tracks the
uninterrupted run within 2e-5 per step.

Root-cause note: ``test_agent_resumes_at_different_scale`` (quarantined
known-failing since PR 1) is folded in here. The failure was never the
checkpoint — the fused-qkv ``jnp.concatenate`` along a model-sharded axis is
miscompiled by the jaxlib 0.4.x SPMD partitioner (a pure sharded concat
returns wrong bytes), so EVERY tensor-parallel forward was wrong. The
engines now force ``fused_qkv=False`` whenever the model axis is >1.

Process-isolation note: the tensor-parallel step programs sit in the jaxlib
0.4.x warm-compile-cache crash class (PR 3 root cause: deserialized
CPU-collective executables segfault on execute/free; toggling the
compilation cache mid-suite is ALSO a trigger), so the TP-touching bodies
run as world_size=1 subprocess workers via the mp harness — fresh cache-less
process, crash fails one test. Empirically the dp-only resume-then-train
sequence is ALSO in the crash class when the suite's earlier collective
modules warmed the cache (train_batch on reshard-loaded arrays under a
deserialized executable segfaults), so every engine-driving chaos body lives
in a worker; only the pure-filesystem prune test stays in-process.
"""

import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.checkpoint import atomic
from deepspeed_tpu.elasticity import ElasticAgent

from tests.mp_harness import run_distributed

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# the formerly-quarantined rescale test + 8->4x2->8 chaos (subprocess workers:
# tensor-parallel programs — see the module docstring)
# ---------------------------------------------------------------------------
def test_agent_resumes_at_different_scale():
    """dp8 -> dp4 x tp2 rescale resume + the sharded-concat miscompile
    guard. Body: tests/mp_targets.py elastic_rescale_and_concat_guard."""
    run_distributed("tests.mp_targets:elastic_rescale_and_concat_guard",
                    world_size=1, local_devices=8, timeout=420)


def test_chaos_resize_8_4_8_continuity():
    """Seeded kills at steps 2 and 5; resume 8 -> 4x2 -> 8 with overlapped
    snapshots; per-step losses within 2e-5 of the uninterrupted run; ZeRO
    state resharded automatically both ways. Body: tests/mp_targets.py
    elastic_chaos_resize_8_4_8."""
    run_distributed("tests.mp_targets:elastic_chaos_resize_8_4_8",
                    world_size=1, local_devices=8, timeout=560)


def test_chaos_equal_scale_bitwise_and_cadence_bound():
    """Seeded SIGTERM, equal scale, bitwise trajectory continuity (losses +
    rng + loss-scale + counters), then the cadence bound (snapshot_interval=2
    loses at most 2 steps) — chained in ONE worker to keep the tier-1 window
    lean. Bodies: tests/mp_targets.py elastic_chaos_equal_scale_bitwise ->
    elastic_chaos_cadence_bounds_lost_steps."""
    run_distributed("tests.mp_targets:elastic_chaos_equal_scale_bitwise",
                    world_size=1, local_devices=8, timeout=560)


# ---------------------------------------------------------------------------
# retention vs the live writer (the prune race fix)
# ---------------------------------------------------------------------------
def test_prune_never_touches_tags_newer_than_committed(tmp_path, devices8):
    """A snapshot tag PUBLISHED by the background writer (no latest swap
    yet) must never be counted toward keep_last — pruning the last
    committed tag under it would leave 'latest' dangling if the fresh
    commit then fails."""
    from deepspeed_tpu.checkpoint.sharded import ShardedCheckpointEngine
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.config import MeshConfig
    from deepspeed_tpu.parallel import build_mesh

    mesh = build_mesh(MeshConfig(data=8), devices=devices8)
    sh = NamedSharding(mesh, P("data", None))
    io = ShardedCheckpointEngine()

    def publish(step, commit):
        state = {"w": jax.device_put(
            jnp.arange(64.0).reshape(8, 8) + step, sh)}
        io.save(state, str(tmp_path / f"elastic-step{step}"),
                meta={"global_steps": step})
        if commit:
            io.commit(f"elastic-step{step}")
        else:
            io._last_path = None  # published tag, pointer untouched

    publish(1, commit=True)
    publish(2, commit=True)   # latest -> elastic-step2 (the committed line)
    publish(4, commit=False)  # live writer's output, commit still pending

    agent = ElasticAgent(None, str(tmp_path), keep_last=1)
    agent._prune()
    tags = atomic.list_tags(str(tmp_path))
    assert "elastic-step4" in tags   # newer than committed: protected
    assert "elastic-step2" in tags   # the committed tag itself: kept
    assert "elastic-step1" not in tags  # committed history beyond keep_last
    assert atomic.read_latest(str(tmp_path)) == "elastic-step2"
