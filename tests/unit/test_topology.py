"""Topology / mesh tests (reference analogue: tests/unit/runtime/pipe/test_topology.py)."""

import pytest

from deepspeed_tpu.config import ConfigError, MeshConfig
from deepspeed_tpu.parallel import (
    ProcessTopology,
    PipelineParallelGrid,
    build_mesh,
    resolve_mesh_dims,
    topology_from_mesh_dims,
)


def test_topology_rank_math():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 4])
    assert topo.world_size() == 8
    assert topo.get_rank(pipe=0, data=0) == 0
    assert topo.get_rank(pipe=0, data=3) == 3
    assert topo.get_rank(pipe=1, data=0) == 4
    coord = topo.get_coord(5)
    assert coord.pipe == 1 and coord.data == 1


def test_topology_axis_lists():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 2])
    # data comm lists: ranks varying only in data
    lists = topo.get_axis_comm_lists("data")
    assert lists == [[0, 1], [2, 3]]
    lists = topo.get_axis_comm_lists("pipe")
    assert lists == [[0, 2], [1, 3]]


def test_topology_filter_and_axis_list():
    topo = ProcessTopology(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.get_axis_list("model", 0) == [0, 2, 4, 6]
    assert topo.filter_match(pipe=1, model=1) == [5, 7]


def test_topology_rank_repr():
    topo = ProcessTopology(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.get_rank_repr(0) == "model_00"
    assert topo.get_rank_repr(1) == "model_01"


def test_resolve_mesh_dims_infer():
    dims = resolve_mesh_dims(MeshConfig(), 8)
    assert dims["data"] == 8
    assert dims["model"] == dims["pipe"] == dims["seq"] == dims["expert"] == 1

    dims = resolve_mesh_dims(MeshConfig(model=2), 8)
    assert dims["data"] == 4 and dims["model"] == 2


def test_resolve_mesh_dims_errors():
    with pytest.raises(ConfigError):
        resolve_mesh_dims(MeshConfig(data=3, model=2), 8)
    with pytest.raises(ConfigError):
        resolve_mesh_dims(MeshConfig(model=3), 8)


def test_build_mesh(devices8):
    mesh = build_mesh(MeshConfig(data=4, model=2), devices=devices8)
    assert mesh.shape["data"] == 4
    assert mesh.shape["model"] == 2
    assert set(mesh.axis_names) == {"pipe", "data", "expert", "seq", "model"}


def test_pipeline_grid():
    topo = topology_from_mesh_dims({"pipe": 2, "data": 2, "model": 2})
    grid = PipelineParallelGrid(topo)
    assert grid.pipe_parallel_size == 2
    assert grid.data_parallel_size == 2
    assert grid.is_first_stage(0)
    assert grid.is_last_stage(7)
    assert grid.stage_of_rank(4) == 1
    # dp group of rank 0: same pipe/model coords, varying data
    assert grid.dp_group_of_rank(0) == [0, 2]
