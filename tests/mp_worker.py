"""Worker entry for the multi-process harness: force the CPU platform with this
process's virtual device count, join the distributed rendezvous through the
framework's own ``init_distributed``, then run the target function."""

import importlib
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count="
      f"{os.environ.get('DS_TPU_LOCAL_DEVICES', '4')}").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax.shard_map compat on 0.4.x jaxlibs (same shim conftest installs for
# in-process tests): worker bodies build shard_map engine programs
from deepspeed_tpu.utils import jax_compat as _jax_compat  # noqa: E402

_jax_compat.install()

import deepspeed_tpu.comm as dist  # noqa: E402


def main():
    target = sys.argv[1]
    mod_name, fn_name = target.split(":")
    dist.init_distributed()
    fn = getattr(importlib.import_module(mod_name), fn_name)
    fn()
    print(f"WORKER_OK {jax.process_index()}", flush=True)


if __name__ == "__main__":
    main()
