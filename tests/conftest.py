"""Test harness configuration.

The reference simulates multi-node as multi-process on localhost
(``tests/unit/common.py:86`` DistributedExec). On TPU we instead virtualize: force the
CPU platform with 8 XLA host devices, so every test sees an 8-device mesh and the same
SPMD programs that run on a TPU slice compile and execute here. This must run before
jax is imported anywhere.
"""

import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell presets JAX_PLATFORMS=axon (TPU)

_COLLECTIVE_FLAGS = ("--xla_cpu_collective_call_terminate_timeout_seconds=600"
                     " --xla_cpu_collective_timeout_seconds=600")


def _collective_flags_supported():
    """XLA hard-aborts the process on unknown XLA_FLAGS, so the collective
    timeout flags (absent from older jaxlibs) must be probed in a subprocess
    before we inject them. Cached per jaxlib version."""
    import jaxlib

    cache = os.path.join(os.path.dirname(__file__), ".jax_cache",
                         f"xla_flag_probe-{jaxlib.__version__}")
    if os.path.exists(cache):
        return open(cache).read().strip() == "yes"
    env = dict(os.environ,
               XLA_FLAGS=_COLLECTIVE_FLAGS, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); jax.devices()"],
            env=env, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, OSError):
        return False  # transient (timeout, load): skip flags now, re-probe next run
    ok = proc.returncode == 0
    if ok or b"Unknown flags" in proc.stderr:
        # only cache definitive answers; a flaky crash shouldn't permanently
        # disable the collective-timeout flags for this jaxlib
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        with open(cache, "w") as f:
            f.write("yes" if ok else "no")
    return ok


_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"
if ("xla_cpu_collective_call_terminate_timeout_seconds" not in _flags
        and _collective_flags_supported()):
    # 8 emulated devices share this box's core(s); under load the default 40s
    # collective rendezvous can fire spuriously and SIGABRT the whole suite
    _flags += " " + _COLLECTIVE_FLAGS
os.environ["XLA_FLAGS"] = _flags.strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The axon boot hook (sitecustomize) programmatically sets jax_platforms="axon,cpu",
# which overrides the env var — force CPU at the config level before backend init.
jax.config.update("jax_platforms", "cpu")

# jax.shard_map compat on 0.4.x jaxlibs — installed before test modules import
# (tests do `from jax import shard_map` at module scope)
from deepspeed_tpu.utils import jax_compat as _jax_compat  # noqa: E402

_jax_compat.install()

# Persistent compilation cache: the suite compiles hundreds of small SPMD
# programs (this box has ONE core); identical programs across runs hit the disk
# cache instead of recompiling, cutting repeat wall-clock by minutes.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _collect_cycles():
    """Engines captured in jit closures die by CYCLE collection, not refcount;
    collecting between tests keeps live-buffer accounting (e.g.
    test_destroy_releases_device_buffers) independent of test order."""
    yield
    import gc

    gc.collect()


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture
def mesh8(devices8):
    """Canonical 8-device mesh: pure data-parallel by default."""
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import MeshConfig

    return build_mesh(MeshConfig(), devices=devices8)


@pytest.fixture
def mesh_2d(devices8):
    """data=4 x model=2 mesh for TP tests."""
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import MeshConfig

    return build_mesh(MeshConfig(data=4, model=2), devices=devices8)
