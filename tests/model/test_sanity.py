"""Model/integration tier (reference ``tests/model/`` — BingBertSquad /
Megatron sanity runs): one real end-to-end convergence + resume + serve flow
on a small-but-not-toy model. Heavier than unit tests; marked slow.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import CausalLM, TransformerConfig

pytestmark = pytest.mark.slow


def _corpus(vocab, n, s, seed=0):
    """Synthetic 'language': next token = (3 * tok + 7) % vocab with noise,
    so a real model can actually learn structure (loss well below uniform)."""
    rng = np.random.RandomState(seed)
    first = rng.randint(0, vocab, (n, 1))
    rows = [first]
    for _ in range(s - 1):
        nxt = (3 * rows[-1] + 7) % vocab
        noise = rng.randint(0, vocab, nxt.shape)
        mask = rng.rand(*nxt.shape) < 0.1
        rows.append(np.where(mask, noise, nxt))
    return np.concatenate(rows, axis=1).astype(np.int32)


def test_end_to_end_train_resume_serve(tmp_path, devices8):
    import jax.numpy as jnp

    vocab, s = 64, 32
    model_kw = dict(vocab_size=vocab, max_seq_len=s, n_layers=4, n_heads=4,
                    d_model=64, d_ff=128, compute_dtype=jnp.float32)
    config = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 5,
                                 "warmup_max_lr": 3e-3}},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
        "mesh": {"data": 8},
        "steps_per_print": 10 ** 9,
    }
    data = _corpus(vocab, 512, s)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(TransformerConfig(**model_kw)), config=config)

    rng = np.random.RandomState(1)
    losses = []
    for step in range(30):
        rows = rng.randint(0, len(data), 8)
        losses.append(float(engine.train_batch(
            batch={"input_ids": data[rows]})))
    uniform = np.log(vocab)
    assert losses[-1] < 0.6 * uniform, (losses[0], losses[-1])
    assert np.mean(losses[-5:]) < np.mean(losses[:5])

    # ---- checkpoint -> resume continues from the same loss level ------------
    engine.save_checkpoint(str(tmp_path), tag="sanity")
    resumed, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(TransformerConfig(**model_kw)), config=config)
    resumed.load_checkpoint(str(tmp_path), tag="sanity")
    rows = rng.randint(0, len(data), 8)
    batch = {"input_ids": data[rows]}
    la = float(engine.eval_batch(batch))
    lb = float(resumed.eval_batch(batch))
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    assert resumed.global_steps == engine.global_steps

    # ---- serve the trained weights ------------------------------------------
    inf = deepspeed_tpu.init_inference(
        CausalLM(TransformerConfig(**model_kw)), dtype="float32",
        max_tokens=s)
    inf.load_checkpoint(str(tmp_path), tag="sanity")
    prompt = data[:2, :8]
    out = inf.generate(prompt, max_new_tokens=8, greedy=True)
    assert out.shape == (2, 16)
    # the learned structure shows: greedy continuation mostly follows the rule
    pred = np.asarray(out[:, 8:])
    expect = (3 * np.asarray(out[:, 7:-1]) + 7) % vocab
    agree = float((pred == expect).mean())
    assert agree > 0.5, agree


def test_real_text_byte_lm(devices8):
    """Real-workload tier (VERDICT r4 weak #7: the Markov corpus is synthetic;
    the reference's model tier trains on real data). Byte-level LM over the
    repo's own English prose — real natural-language statistics, no network.
    The bar: beat the byte-unigram entropy of the corpus (a model that only
    learned marginal byte frequencies), which proves structure was learned,
    not just frequency."""
    import os

    import jax.numpy as jnp

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    text = b""
    for fn in ("README.md", "SURVEY.md", "PERF.md"):
        p = os.path.join(root, fn)
        if os.path.isfile(p):
            with open(p, "rb") as f:
                text += f.read()
    assert len(text) > 50_000, "corpus unexpectedly small"
    data = np.frombuffer(text, np.uint8).astype(np.int32)

    s = 64
    n_win = (len(data) - 1) // s
    windows = data[:n_win * s].reshape(n_win, s)

    # byte-unigram entropy of this corpus = the frequency-only baseline
    counts = np.bincount(data, minlength=256).astype(np.float64)
    probs = counts / counts.sum()
    unigram = float(-(probs[probs > 0] * np.log(probs[probs > 0])).sum())

    config = {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "mesh": {"data": 8},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=CausalLM(TransformerConfig(
            vocab_size=256, max_seq_len=s, n_layers=4, n_heads=4,
            d_model=128, d_ff=256, compute_dtype=jnp.float32)),
        config=config)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(40):
        rows = rng.randint(0, n_win, 16)
        losses.append(float(engine.train_batch(
            batch={"input_ids": windows[rows]})))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # below the unigram entropy = learned real sequential structure
    assert np.mean(losses[-5:]) < unigram, (np.mean(losses[-5:]), unigram)
