"""Headline benchmark: GPT-2 (350M-class) training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.40 (the driver's north-star: ZeRO-3 OPT-13B >40% MFU
on v4-256; single-chip proxy here is dense-LM training MFU).
"""

import json
import os
import sys
import time

import numpy as np


# bf16 peak TFLOP/s per chip by TPU generation
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}


def detect_peak_tflops():
    import jax

    kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    for key, peak in PEAK_TFLOPS.items():
        if key in kind:
            return peak
    env = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    return PEAK_TFLOPS.get(env, 197.0)


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    n_chips = len(jax.devices())

    # GPT-2 medium-class decoder (~350M params), bf16 compute, remat off (fits).
    cfg = TransformerConfig(
        vocab_size=50304,  # padded to a multiple of 128 for MXU-friendly head matmul
        max_seq_len=1024,
        n_layers=24,
        n_heads=16,
        d_model=1024,
        d_ff=4096,
        compute_dtype=jnp.bfloat16,
        attention_impl=os.environ.get("BENCH_ATTN", "xla"),
        remat=os.environ.get("BENCH_NOREMAT", "") != "1",
        remat_policy=os.environ.get("BENCH_REMAT", "minimal"),
        scan_layers=os.environ.get("BENCH_SCAN", "1") == "1",
        fused_ce=os.environ.get("BENCH_FUSED_CE", "1") == "1",
    )
    model = CausalLM(cfg)

    batch_size = int(os.environ.get("BENCH_BATCH", "12")) * n_chips
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    config = {
        "train_batch_size": batch_size,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1 if n_chips > 1 else 0},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32)}

    def one_step():
        # fused path: fwd+bwd+optimizer in ONE device dispatch (engine.train_batch)
        return engine.train_batch(batch=batch)

    def sync():
        # On the axon-tunneled platform block_until_ready doesn't actually block;
        # a scalar host readback of the final params is the reliable fence.
        leaf = jax.tree_util.tree_leaves(engine.params)[0]
        np.asarray(jax.device_get(leaf.ravel()[0]))

    # warmup / compile
    for _ in range(2):
        loss = one_step()
    sync()

    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = one_step()
    sync()
    dt = time.perf_counter() - t0

    tokens = batch_size * seq_len * n_steps
    tokens_per_sec = tokens / dt
    tokens_per_sec_per_chip = tokens_per_sec / n_chips

    # training flops ~= 6 * n_params * tokens (fwd 2x + bwd 4x)
    n_params = engine.num_parameters
    flops_per_token = 6.0 * n_params
    achieved_tflops = tokens_per_sec_per_chip * flops_per_token / 1e12
    peak = detect_peak_tflops()
    mfu = achieved_tflops / peak

    result = {
        "metric": "gpt2_350m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "achieved_tflops": round(achieved_tflops, 2),
            "peak_tflops": peak,
            "n_params_m": round(n_params / 1e6, 1),
            "batch": batch_size,
            "seq": seq_len,
            "steps": n_steps,
            "final_loss": round(float(loss), 4),
            "n_chips": n_chips,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
