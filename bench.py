"""Headline benchmark: GPT-2 (350M-class) training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.40 (the driver's north-star: ZeRO-3 OPT-13B >40% MFU
on v4-256; single-chip proxy here is dense-LM training MFU).

Wedge-proof design (round 3): the axon TPU tunnel can wedge `jax.devices()` for
hours (see PERF.md "Environment caveat"). The parent process therefore NEVER
imports jax. It (1) probes the backend in a killable subprocess with a 45 s
timeout, (2) runs the real benchmark in a second subprocess with a global
timeout, and (3) always prints a valid JSON record — on any failure the record
carries value=0 / vs_baseline=0 plus an "error" field, and the exit code is 0 so
the driver records a parseable result instead of a traceback.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np


# bf16 peak TFLOP/s per chip by TPU generation
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}

METRIC = "gpt2_350m_train_tokens_per_sec_per_chip"
UNIT = "tokens/s/chip"
DEFAULTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_defaults.json")

def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


PROBE_TIMEOUT_S = _env_int("BENCH_PROBE_TIMEOUT", 45)
RUN_TIMEOUT_S = _env_int("BENCH_TIMEOUT", 1800)


def _error_record(msg):
    return {
        "metric": METRIC,
        "value": 0.0,
        "unit": UNIT,
        "vs_baseline": 0.0,
        "error": msg[-2000:],
    }


def _stamp(record, config=None):
    """Provenance stamp (git SHA, config hash, backend) so a proxy run can
    never be confused with an on-chip number (BENCH_r03–r05 lesson). Uses
    ``tools/_common.run_stamp``; a best-effort fallback keeps this file's
    driver contract standalone if tools/ is ever absent."""
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        from _common import stamp_record

        return stamp_record(record, config)
    except Exception:
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=10).stdout.strip()
        except (OSError, subprocess.SubprocessError):
            sha = "unknown"
        record["provenance"] = {"git_sha": sha or "unknown"}
        return record


def _run_subprocess(args, timeout_s, env=None):
    """Run argv in its own session; on timeout terminate the process group.

    SIGTERM first with a grace period (a killed-mid-session TPU process wedges
    the tunnel for hours — give libtpu a chance to release the claim), then
    SIGKILL. Children run with -u so a result printed before a wedge is in the
    pipe, not lost in a userspace buffer.

    Returns (rc_or_None, stdout, stderr); rc None means timed out/killed.
    """
    proc = subprocess.Popen(
        [args[0], "-u"] + args[1:],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
        env=env,
    )
    def _text(x):
        if isinstance(x, bytes):
            return x.decode("utf-8", "replace")
        return x or ""

    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired as te:
        # Keep whatever the child already wrote — even if it never dies
        # (D-state on a wedged TPU driver), a result printed before the wedge
        # is recoverable from the exception's partial-output buffers.
        out, err = _text(te.stdout), _text(te.stderr)
        for sig, grace in ((signal.SIGTERM, 15), (signal.SIGKILL, 10)):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                out, err = proc.communicate(timeout=grace)
                break
            except subprocess.TimeoutExpired as te2:
                out = _text(te2.stdout) or out
                err = _text(te2.stderr) or err
            except Exception:
                break
        return None, out, err


def _maybe_force_cpu():
    """BENCH_FORCE_CPU=1: pin jax to the host CPU backend.

    The axon boot hook programmatically sets jax_platforms="axon,cpu", which
    overrides the JAX_PLATFORMS env var — forcing CPU must happen at the config
    level after import. Used to exercise the full bench pipeline when the TPU
    tunnel is unavailable (the result still prints, with platform noted).
    """
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache (shared with tools/): an identical program
    # compiled by an earlier sweep/session is reused — less claim time spent
    # in remote_compile. Harmless no-op if the plugin can't serialize.
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass


def probe():
    """Child mode: touch the backend (jax.devices()); exit 0 iff it answers.

    A down-but-not-wedged tunnel makes jax fall back to the CPU backend
    (jax_platforms="axon,cpu"); that must read as probe FAILURE — a CPU
    "benchmark" would report a bogus near-zero number as valid — unless the
    caller explicitly forced CPU with BENCH_FORCE_CPU=1.
    """
    _maybe_force_cpu()
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu" and os.environ.get("BENCH_FORCE_CPU") != "1":
        print(f"probe: backend fell back to '{platform}' (TPU unavailable)", file=sys.stderr)
        return 3
    return 0


def detect_peak_tflops(kind):
    kind = kind.lower().replace(" ", "")
    for key, peak in PEAK_TFLOPS.items():
        if key in kind:
            return peak
    env = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    return PEAK_TFLOPS.get(env, 197.0)


def run_benchmark():
    """Child mode: the actual measurement. Prints the one JSON result line."""
    _maybe_force_cpu()
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    if os.environ.get("BENCH_CPU_PROXY") == "1":
        return run_cpu_proxy()

    n_chips = len(jax.devices())

    # GPT-2 medium-class decoder (~350M params), bf16 compute.
    # BENCH_FLASH_BLOCKS="bqxbkv[:bq_bwd x bkv_bwd]" tunes the pallas tiles
    flash_blocks = {}
    spec = os.environ.get("BENCH_FLASH_BLOCKS", "")
    if spec:
        from deepspeed_tpu.ops.flash_attention import parse_block_spec

        bq, bkv, bqb, bkvb = parse_block_spec(spec)
        flash_blocks = {"flash_block_q": bq, "flash_block_kv": bkv,
                        "flash_block_q_bwd": bqb, "flash_block_kv_bwd": bkvb}

    # sweep-chosen defaults (tools/sweep_bench.py writes the measured winner
    # to bench_defaults.json); explicit env vars still override.
    # BENCH_SAFE=1 ignores the tuned winner entirely — the parent's fallback
    # when the winner config failed to produce a number (e.g. the unrolled
    # noremat program failing a cold-cache compile): a base-config ~26k tok/s
    # result beats a 0.0 record.
    tuned = {}
    tuned_cfg = {}
    tuned_batch = None
    defaults_path = DEFAULTS_PATH
    if os.environ.get("BENCH_SAFE") == "1":
        defaults_path = ""
        print("# BENCH_SAFE=1: ignoring bench_defaults.json", file=sys.stderr)
    if defaults_path and os.path.isfile(defaults_path):
        try:
            with open(defaults_path) as f:
                rec = json.load(f)
            tuned = dict(rec.get("model_overrides", {}))
            tuned_cfg = dict(rec.get("config_overrides", {}))
            tuned_batch = rec.get("batch")
            print(f"# bench_defaults.json: {rec.get('variant')} "
                  f"({rec.get('tokens_per_s')} tok/s measured)",
                  file=sys.stderr)
        except (ValueError, OSError) as e:
            print(f"# bench_defaults.json ignored: {e}", file=sys.stderr)

    def opt(env_name, key, default, parse=str):
        """Priority: explicit env var > sweep-tuned default > built-in."""
        if os.environ.get(env_name):
            return parse(os.environ[env_name])
        if key in tuned:
            return tuned[key]
        return parse(default)

    # tuned keys handled by an opt()/env path above must not pass through
    # twice; everything else flows generically so a future sweep variant's
    # winning override is fully applied (dropped keys would silently bench a
    # config that was never the measured winner)
    OPT_HANDLED = {"attention_impl", "attention_logits_dtype", "remat_policy",
                   "scan_layers", "fused_ce"}
    # every kwarg the TransformerConfig(...) call below passes explicitly —
    # a tuned key colliding with one of these would raise "multiple values
    # for keyword argument" and crash the headline bench
    EXPLICIT = {"vocab_size", "max_seq_len", "n_layers", "n_heads",
                "d_model", "d_ff", "compute_dtype", "remat"} | OPT_HANDLED
    import dataclasses as _dc

    cfg_fields = {f.name for f in _dc.fields(TransformerConfig)}
    passthrough = {k: v for k, v in tuned.items()
                   if k not in EXPLICIT and k not in flash_blocks
                   and k in cfg_fields}
    dropped = set(tuned) - EXPLICIT - set(flash_blocks) - set(passthrough)
    if dropped:
        print(f"# bench_defaults.json keys not applicable, ignored: "
              f"{sorted(dropped)}", file=sys.stderr)

    cfg = TransformerConfig(
        vocab_size=50304,  # padded to a multiple of 128 for MXU-friendly head matmul
        max_seq_len=1024,
        n_layers=24,
        n_heads=16,
        d_model=1024,
        d_ff=4096,
        compute_dtype=jnp.bfloat16,
        attention_impl=opt("BENCH_ATTN", "attention_impl", "xla"),
        attention_logits_dtype=opt(
            "BENCH_ATTN_LOGITS", "attention_logits_dtype", "fp32"),
        # env > tuned > default-on (remat is EXPLICIT so the tuned key can't
        # flow through passthrough; consuming it here keeps a noremat sweep
        # winner actually running without remat)
        remat=((os.environ["BENCH_NOREMAT"] != "1")
               if os.environ.get("BENCH_NOREMAT")
               else bool(tuned.get("remat", True))),
        remat_policy=opt("BENCH_REMAT", "remat_policy", "minimal"),
        scan_layers=bool(opt("BENCH_SCAN", "scan_layers", "1",
                             lambda v: v == "1")),
        fused_ce=bool(opt("BENCH_FUSED_CE", "fused_ce", "1",
                          lambda v: v == "1")),
        **passthrough,
        **flash_blocks,  # explicit BENCH_FLASH_BLOCKS beats tuned tiles
    )
    model = CausalLM(cfg)

    batch_size = int(os.environ.get("BENCH_BATCH", "")
                     or tuned_batch or 12) * n_chips
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    if os.environ.get("BENCH_DRY") == "1":
        # resolved-config dry run: prints exactly what a real run would
        # build (bench_defaults adoption, env precedence, tile passthrough)
        # without compiling anything — the cheap check that the persisted
        # sweep winner actually reaches the TransformerConfig
        print(json.dumps({
            "dry": True, "batch": batch_size, "seq": seq_len,
            "config": {f.name: repr(getattr(cfg, f.name))
                       for f in _dc.fields(cfg)}}))
        return 0
    config = {
        "train_batch_size": batch_size,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1 if n_chips > 1 else 0},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
        **tuned_cfg,  # sweep-measured engine-config deltas (e.g. noclip)
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32)}

    def one_step():
        # fused path: fwd+bwd+optimizer in ONE device dispatch (engine.train_batch)
        return engine.train_batch(batch=batch)

    def sync():
        # On the axon-tunneled platform block_until_ready doesn't actually block;
        # a scalar host readback of the final params is the reliable fence.
        leaf = jax.tree_util.tree_leaves(engine.params)[0]
        np.asarray(jax.device_get(leaf.ravel()[0]))

    # warmup / compile
    for _ in range(2):
        loss = one_step()
    sync()

    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = one_step()
    sync()
    dt = time.perf_counter() - t0

    tokens = batch_size * seq_len * n_steps
    tokens_per_sec = tokens / dt
    tokens_per_sec_per_chip = tokens_per_sec / n_chips

    # training flops ~= 6 * n_params * tokens (fwd 2x + bwd 4x)
    n_params = engine.num_parameters
    flops_per_token = 6.0 * n_params
    achieved_tflops = tokens_per_sec_per_chip * flops_per_token / 1e12
    peak = detect_peak_tflops(jax.devices()[0].device_kind)
    mfu = achieved_tflops / peak

    forced_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    result = {
        "metric": METRIC,
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": UNIT,
        # A forced-CPU debug run must never read as a real TPU datum at the
        # top level: vs_baseline is zeroed and the mode is marked.
        "vs_baseline": 0.0 if forced_cpu else round(mfu / 0.40, 4),
        # numerics self-incrimination next to the run stamp: a "fast" run
        # that silently skipped half its steps (or tripped the health
        # watchdog) says so in its own artifact
        "numerics": {
            "skipped_steps": engine.skipped_steps,
            "final_loss_scale": float(engine.loss_scale),
            "health_anomalies": engine.health.anomaly_count,
        },
        "extra": {
            "mfu": round(mfu, 4),
            "achieved_tflops": round(achieved_tflops, 2),
            "peak_tflops": peak,
            "n_params_m": round(n_params / 1e6, 1),
            "batch": batch_size,
            "seq": seq_len,
            "steps": n_steps,
            "final_loss": round(float(loss), 4),
            "n_chips": n_chips,
            "platform": jax.devices()[0].platform,
        },
    }
    if forced_cpu:
        result["forced_cpu"] = True
    _stamp(result, config=dict(config, batch=batch_size, seq=seq_len))
    print(json.dumps(result))
    return 0


def run_cpu_proxy():
    """CPU-mesh proxy measurement for when the TPU tunnel is down.

    A scaled-down model (the headline GPT-2 350M shape is hours-per-step on
    one CPU core) through the REAL fused train_batch path. The number is a
    trajectory signal — "the training path still works and runs at N tok/s
    on the host" — NOT comparable to TPU rows: the record carries
    ``"backend": "cpu_proxy"`` and vs_baseline stays 0.0. Replaces the old
    behavior of emitting value 0.0 + an error string, which made the bench
    trajectory read as empty for every tunnel-wedged round.
    """
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig
    import jax.numpy as jnp

    cfg = TransformerConfig(
        vocab_size=50304, max_seq_len=256, n_layers=4, n_heads=4,
        d_model=256, d_ff=1024, compute_dtype=jnp.bfloat16,
        remat=False, scan_layers=True, fused_ce=True, attention_impl="xla")
    model = CausalLM(cfg)
    batch_size = _env_int("BENCH_PROXY_BATCH", 2)
    seq_len = 256
    config = {
        "train_batch_size": batch_size,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32)}
    for _ in range(2):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(engine.params)[0])
    n_steps = _env_int("BENCH_PROXY_STEPS", 3)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(engine.params)[0])
    dt = time.perf_counter() - t0
    tokens_per_sec = batch_size * seq_len * n_steps / dt
    result = {
        "metric": METRIC,
        "value": round(tokens_per_sec, 1),
        "unit": UNIT,
        "vs_baseline": 0.0,  # a host-CPU proxy can never claim MFU progress
        "backend": "cpu_proxy",
        "numerics": {
            "skipped_steps": engine.skipped_steps,
            "final_loss_scale": float(engine.loss_scale),
            "health_anomalies": engine.health.anomaly_count,
        },
        "extra": {
            "note": "TPU tunnel unavailable; CPU-mesh proxy on a scaled-down "
                    "model (n_layers=4, d_model=256, seq=256) through the "
                    "real fused train_batch path",
            "n_params_m": round(engine.num_parameters / 1e6, 1),
            "batch": batch_size,
            "seq": seq_len,
            "steps": n_steps,
            "final_loss": round(float(loss), 4),
            "platform": jax.devices()[0].platform,
        },
    }
    _stamp(result, config=dict(config, seq=seq_len))
    print(json.dumps(result))
    return 0


def main():
    if "--probe" in sys.argv:
        return probe()
    if "--child" in sys.argv:
        return run_benchmark()
    if os.environ.get("BENCH_DRY") == "1":
        # config-resolution check only: never touch the tunnel
        os.environ["BENCH_FORCE_CPU"] = "1"
        return run_benchmark()

    # Parent: no jax import here, ever.
    rc, out, err = _run_subprocess(
        [sys.executable, os.path.abspath(__file__), "--probe"], PROBE_TIMEOUT_S
    )
    if rc is None or rc != 0:
        # Tunnel down/wedged. A 0.0-with-error record made every wedged
        # round read as an empty bench trajectory; instead fall back to a
        # CPU-mesh proxy measurement, recorded as backend="cpu_proxy"
        # (vs_baseline stays 0.0 — a host number never claims MFU progress).
        reason = (f"TPU backend probe timed out after {PROBE_TIMEOUT_S}s "
                  f"(tunnel wedged?)" if rc is None else
                  f"TPU backend probe failed (rc={rc}): {err.strip()[-500:]}")
        print(f"# {reason}; falling back to CPU-mesh proxy", file=sys.stderr)
        prc, pout, perr = _run_subprocess(
            [sys.executable, os.path.abspath(__file__), "--child"],
            _env_int("BENCH_PROXY_TIMEOUT", 900),
            env={**os.environ, "BENCH_FORCE_CPU": "1", "BENCH_CPU_PROXY": "1"})
        for line in reversed((pout or "").strip().splitlines()):
            try:
                cand = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(cand, dict) and "metric" in cand:
                cand.setdefault("extra", {})["tpu_probe_error"] = reason
                print(json.dumps(cand))
                return 0
        # proxy also failed: keep the old explicit error record
        print(json.dumps(_error_record(
            f"{reason}; cpu proxy also failed (rc={prc}): "
            f"{(perr or '').strip()[-500:]}")))
        return 0

    # Claim-handoff settle: the axon tunnel serves one claim, and a new TPU
    # process starting <~10 s after the previous one exits can wedge it for
    # hours (observed 2026-07-31; a ~60 s gap worked). The probe child just
    # released a claim — give the tunnel time to notice before the
    # measurement child knocks.
    if os.environ.get("BENCH_FORCE_CPU") != "1":
        time.sleep(_env_int("BENCH_HANDOFF_DELAY", 45))

    def run_child(extra_env=None):
        rc, out, err = _run_subprocess(
            [sys.executable, os.path.abspath(__file__), "--child"],
            RUN_TIMEOUT_S,
            env={**os.environ, **extra_env} if extra_env else None)
        # Find the child's result line (last stdout line parsing with
        # "metric"). Scanned even on timeout: a child that measured, printed
        # its result, then wedged in backend teardown still produced a real
        # number — keep it.
        for line in reversed(out.strip().splitlines()):
            try:
                cand = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(cand, dict) and "metric" in cand:
                return rc, cand, err
        return rc, None, err

    rc, record, err = run_child()
    used_defaults = (os.environ.get("BENCH_SAFE") != "1"
                     and os.path.isfile(DEFAULTS_PATH))
    # Safe-config fallback (VERDICT r4 weak #5): only when the tuned child
    # EXITED without a number (a compile crash of the aggressive
    # unrolled/noremat winner) — a ~26k tok/s base number beats a 0.0
    # record. NOT on timeout (rc None): that is a tunnel wedge, a retry
    # against it is futile and would double the worst-case wall time past
    # an outer driver budget, which is worse than a prompt 0.0 record.
    if record is None and rc is not None and used_defaults:
        first_err = err.strip()[-1500:]
        print(f"# tuned-config child exited rc={rc} with no result; "
              f"retrying with BENCH_SAFE=1. First run stderr tail:\n"
              f"{first_err}", file=sys.stderr)
        time.sleep(_env_int("BENCH_HANDOFF_DELAY", 45))
        rc, record, err = run_child({"BENCH_SAFE": "1"})
        if record is not None:
            record.setdefault("extra", {})["safe_fallback"] = True
        else:
            # keep BOTH failures' evidence in the final record
            err = (f"[tuned] {first_err} [safe] {err.strip()[-700:]}")

    if record is None:
        if rc is None:
            print(json.dumps(_error_record(f"benchmark timed out after {RUN_TIMEOUT_S}s")))
        else:
            print(json.dumps(_error_record(
                f"benchmark produced no JSON result (rc={rc}): {err.strip()[-1500:]}")))
        return 0

    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
