"""Config autotuner: sweep mesh shape x micro-batch x ZeRO stage x remat.

Reference: ``deepspeed/autotuning/`` (2.7k LoC — ``autotuner.py:404 tune``,
``tuner/{index_based,model_based}_tuner.py``, ``tuner/cost_model.py``,
experiment ``scheduler.py``): the reference launches whole training jobs per
config and fits a cost model over the results. On TPU the compiler replaces
most of that machinery:

1. **compile-prune**: every candidate's train step is jit-lowered; XLA's
   ``memory_analysis`` gives exact peak memory per candidate WITHOUT running a
   step, so OOM configs are discarded for free (the reference has to crash a
   job to learn this);
2. **cost-model rank**: ``cost_analysis`` flops/bytes -> a roofline time
   estimate orders the survivors;
3. **measure**: only the top-k candidates run real timed steps.

Emits the winning config as plain JSON (the reference's
``autotuning_results/`` contract).
"""

import dataclasses
import itertools
import json
import time

import numpy as np

from ..utils.logging import log_dist, logger


@dataclasses.dataclass
class TuneResult:
    config: dict
    peak_bytes: int = -1
    est_time: float = -1.0
    measured_tokens_per_s: float = -1.0
    status: str = "pending"  # pruned-oom | compile-failed | estimated | measured
    # measurement environment (batch shape, device count/memory, roofline
    # constants) — part of the ledger key, see key()
    env: dict = dataclasses.field(default_factory=dict)

    def row(self):
        zero = self.config.get("zero_optimization", {})
        return {
            "mesh": self.config.get("mesh"),
            "micro": self.config.get("train_micro_batch_size_per_gpu"),
            "gas": self.config.get("gradient_accumulation_steps"),
            "zero": zero.get("stage"),
            "offload": zero.get("offload_optimizer", {}).get("device"),
            "remat": self.config.get("_remat"),
            "peak_gb": round(self.peak_bytes / 1e9, 3) if self.peak_bytes >= 0 else None,
            "est_ms": round(self.est_time * 1e3, 2) if self.est_time >= 0 else None,
            "tok_s": round(self.measured_tokens_per_s, 1)
            if self.measured_tokens_per_s >= 0 else None,
            "status": self.status,
        }

    def key(self):
        """Stable identity of the candidate (ledger key). Includes the
        measurement environment — batch shape, device count/memory, roofline
        constants — so a ledger from a different workload or machine is never
        silently replayed."""
        import hashlib

        blob = json.dumps({"config": self.config, "env": self.env},
                          sort_keys=True, default=str)
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_ledger(self):
        return {"key": self.key(), "row": self.row(),
                "peak_bytes": self.peak_bytes, "est_time": self.est_time,
                "measured_tokens_per_s": self.measured_tokens_per_s,
                "status": self.status}

    def restore(self, entry):
        self.peak_bytes = entry["peak_bytes"]
        self.est_time = entry["est_time"]
        self.measured_tokens_per_s = entry["measured_tokens_per_s"]
        self.status = entry["status"]


def _factor_meshes(n_devices, axes=("data", "model")):
    """All 2-axis factorizations of the device count."""
    out = []
    for model in range(1, n_devices + 1):
        if n_devices % model == 0:
            out.append({"data": n_devices // model, "model": model})
    return out


class Autotuner:
    """Sweep-and-measure over engine configs for a given model + batch shape.

    ``model_factory``: () -> model (fresh per candidate; engines own their
    params). ``base_config``: the user's config; tuned keys are overwritten.
    """

    def __init__(self, model_factory, base_config, *, device_memory_bytes=None,
                 peak_flops=None, hbm_bw=None, results_dir=None,
                 zero_stages=None, remats=None, offloads=None, micros=None):
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.device_memory = device_memory_bytes or self._detect_memory()
        # roofline constants for the estimate (defaults: v5e-ish)
        self.peak_flops = peak_flops or 100e12
        self.hbm_bw = hbm_bw or 6e11
        # user-constrained search space (reference autotuning config lets the
        # user scope the sweep, e.g. ``"zero_optimization": {"stage": [1, 2]}``
        # in autotuner.py:404 tune's space) — None means the full default axis
        self.zero_stages = zero_stages
        self.remats = remats
        self.offloads = offloads
        self.micros = micros
        # model-based exploration (reference tuner/model_based_tuner.py):
        # after the top-k measurements, recalibrate the roofline from the
        # observed runs and measure any candidate the calibrated model says
        # beats the measured best. calibration_ records the fitted factor.
        self.model_based = True
        self.explore_topk = 3
        self.calibration_ = None
        # experiment ledger (reference autotuning_results/ contract,
        # autotuner.py:404): every candidate's outcome is appended to
        # <results_dir>/ledger.jsonl as it lands, and a re-run resumes from it
        # (already-explored candidates skip straight to their recorded result)
        self.results_dir = results_dir

    # ------------------------------------------------------------------
    def _ledger_path(self):
        import os

        os.makedirs(self.results_dir, exist_ok=True)
        return os.path.join(self.results_dir, "ledger.jsonl")

    def _load_ledger(self):
        import os

        entries = {}
        if self.results_dir and os.path.isfile(self._ledger_path()):
            with open(self._ledger_path()) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    e = json.loads(line)
                    entries[e["key"]] = e  # last write wins
        return entries

    def _append_ledger(self, res):
        if self.results_dir:
            with open(self._ledger_path(), "a") as f:
                f.write(json.dumps(res.to_ledger()) + "\n")

    @staticmethod
    def _detect_memory():
        from ..accelerator import get_accelerator

        limit = get_accelerator().total_memory()
        return limit or 12 * 2 ** 30  # conservative when the backend won't say

    # ------------------------------------------------------------------
    def search_space(self, n_devices, global_batch):
        zero_stages = self.zero_stages if self.zero_stages is not None \
            else [0, 1, 2, 3]
        # minimal_nomlp: recompute the fc GEMM instead of saving mlp_hidden —
        # the compile-prune stage discards it wherever "minimal" already fits
        remats = self.remats if self.remats is not None \
            else ["minimal", "minimal_nomlp", None]
        offloads = self.offloads if self.offloads is not None else [None, "cpu"]
        micros = [m for m in (self.micros if self.micros is not None
                              else (1, 2, 4, 8, 16))
                  if global_batch % (m * 1) == 0]
        meshes = _factor_meshes(n_devices)
        cands = []
        for mesh, zero, remat, micro, offload in itertools.product(
                meshes, zero_stages, remats, micros, offloads):
            dp = mesh["data"]
            if global_batch % (micro * dp):
                continue
            if offload and zero < 1:
                # optimizer offload needs sharded optimizer state (ZeRO >= 1),
                # matching the reference's offload/stage coupling
                continue
            cfg = dict(self.base_config)
            cfg["mesh"] = mesh
            cfg["zero_optimization"] = {"stage": zero}
            if offload:
                cfg["zero_optimization"]["offload_optimizer"] = {"device": offload}
            cfg["train_batch_size"] = global_batch
            cfg["train_micro_batch_size_per_gpu"] = micro
            # explicit: micro x dp fixes gas via the batch triangle; recording
            # it makes the swept grad-accum dimension visible in the ledger
            cfg["gradient_accumulation_steps"] = global_batch // (micro * dp)
            cfg["_remat"] = remat
            cands.append(cfg)
        return cands

    # ------------------------------------------------------------------
    def _build_engine(self, cfg):
        import deepspeed_tpu

        model = self.model_factory()
        if hasattr(model, "config"):
            model.config.remat = cfg.get("_remat") is not None
            if cfg.get("_remat"):
                model.config.remat_policy = cfg["_remat"]
        clean = {k: v for k, v in cfg.items() if not k.startswith("_")}
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=clean)
        return engine

    def _lower_step(self, engine, batch):
        """Lower+compile the fused fwd_bwd for analysis (the step's hot path)."""
        import jax
        import jax.numpy as jnp

        engine._build_fwd_bwd()
        sharded = engine._shard_batch(
            {k: v[: engine.micro_batch_size * engine.dp_world_size]
             for k, v in batch.items()})
        rng = jax.random.PRNGKey(0)
        lowered = engine._fwd_bwd_fn.lower(
            engine.params, sharded, jnp.asarray(1.0, jnp.float32), rng)
        return lowered.compile(), sharded, rng

    # host link bandwidth proxy for the offload transfer penalty (optimizer
    # step stages grads down + params back over the host link once per
    # GLOBAL batch; ~10 GB/s is a conservative PCIe-class figure)
    HOST_LINK_BW = 1e10

    def _estimate(self, compiled, n_params=0, tokens_micro=0):
        mem = compiled.memory_analysis()
        # subtract donation-aliased bytes: without this the projection
        # double-counts donated buffers and the prune discards exactly the
        # large-micro candidates the tuner exists to find (calibrated on-chip
        # 2026-08-01: projected 18.9 GB passed the real TPU compile on a
        # 16 GB part — see tools/sweep_bench.py HBM_BUDGET)
        peak = (mem.temp_size_in_bytes + mem.argument_size_in_bytes +
                mem.output_size_in_bytes
                - getattr(mem, "alias_size_in_bytes", 0))
        cost = compiled.cost_analysis() or {}
        flops = cost.get("flops", 0.0)
        bytes_ = cost.get("bytes accessed", 0.0)
        # analytic floors: XLA's cost_analysis counts a lax.scan BODY once,
        # not times its trip count, so a scanned-layer model under-reports by
        # ~n_layers x (measured on-chip 2026-08-01: predicted 44x below
        # measured, rank correlation -1.0). A dense-LM fwd+bwd is >= 6
        # flops/param/token; weights move >= 3 x n_params x 2 bytes (fwd
        # read, bwd read, grad write in bf16). The floors restore the
        # magnitude (and with it the cross-micro ordering) without needing
        # to parse the HLO's trip counts.
        if n_params and tokens_micro:
            flops = max(flops, 6.0 * n_params * tokens_micro)
            bytes_ = max(bytes_, 6.0 * n_params)
        est = max(flops / self.peak_flops, bytes_ / self.hbm_bw)
        return peak, est

    def _opt_state_bytes(self, n_params, cfg):
        """Device-resident optimizer bytes the fwd_bwd lowering can't see:
        adam m+v plus the fp32 master, sharded over data for ZeRO >= 1,
        zero when offloaded to the host."""
        zero_cfg = cfg.get("zero_optimization", {})
        if zero_cfg.get("offload_optimizer"):
            return 0
        shard = cfg["mesh"]["data"] if zero_cfg.get("stage", 0) >= 1 else 1
        return 3 * n_params * 4 // shard

    def _offload_penalty(self, n_params, cfg):
        """est_time surcharge per MICRO step for host-offloaded optimizers:
        grads down + params back (2x n_params fp32) once per global batch,
        amortized over the accumulation steps."""
        if not cfg.get("zero_optimization", {}).get("offload_optimizer"):
            return 0.0
        gas = max(cfg.get("gradient_accumulation_steps", 1), 1)
        return (2.0 * n_params * 4 / self.HOST_LINK_BW) / gas

    # ------------------------------------------------------------------
    def tune(self, batch, *, measured_topk=3, measure_steps=3, max_candidates=None):
        """Returns (best_config, [TuneResult...]). ``batch`` must cover the
        largest global micro-batch in the space."""
        import jax

        n_devices = len(jax.devices())
        global_batch = self.base_config.get("train_batch_size") \
            or batch["input_ids"].shape[0]
        cands = self.search_space(n_devices, global_batch)
        if max_candidates:
            cands = cands[:max_candidates]
        env = {
            "batch_shape": {k: list(np.shape(v)) for k, v in batch.items()},
            "n_devices": n_devices,
            "device_memory": self.device_memory,
            "peak_flops": self.peak_flops,
            "hbm_bw": self.hbm_bw,
        }
        ledger = self._load_ledger()
        results = []
        n_resumed = 0
        est_cache = {}   # offload twins share one lowering: the fwd_bwd
        # program is identical (offload only changes the host-side step)
        for cfg in cands:
            res = TuneResult(config=cfg, env=env)
            results.append(res)
            prev = ledger.get(res.key())
            if prev and prev["status"] not in (
                    "pending", "compile-failed", "measure-failed"):
                # resume: skip re-exploring. compile-failed and
                # measure-failed entries ARE replayed — the failure may have
                # been a since-fixed bug or a transient abort (the emulated
                # platform's spurious collective aborts), and retrying is
                # cheap relative to permanently blacklisting a candidate
                res.restore(prev)
                n_resumed += 1
                continue
            zero_cfg = dict(cfg.get("zero_optimization", {}))
            zero_cfg.pop("offload_optimizer", None)
            est_key = json.dumps(
                {**{k: v for k, v in cfg.items() if k != "zero_optimization"},
                 "zero_optimization": zero_cfg},
                sort_keys=True, default=str)
            try:
                if est_key in est_cache:
                    fwd_peak, fwd_est, n_params = est_cache[est_key]
                else:
                    # COMPILE-ONLY estimation engine (abstract_init): params/
                    # opt-state are ShapeDtypeStructs, so estimation holds
                    # ZERO device bytes. This retires the r4 failure mode
                    # for good — estimation engines each pinned ~9x n_params
                    # bytes via engine<->jit-closure gc cycles and exhausted
                    # the 16 GB chip before the measure phase (2026-08-01:
                    # every measure -> RESOURCE_EXHAUSTED -> "no viable
                    # candidate"). Built from the offload-STRIPPED config:
                    # the fwd_bwd program is identical (offload only changes
                    # the host-side step, accounted analytically below) and
                    # abstract engines don't support host masters anyway.
                    from ..runtime.engine import abstract_init

                    est_cfg = dict(cfg)
                    est_cfg["zero_optimization"] = zero_cfg
                    with abstract_init():
                        engine = self._build_engine(est_cfg)
                    try:
                        compiled, _, _ = self._lower_step(engine, batch)
                        n_params = engine.num_parameters
                        tokens_micro = (engine.micro_batch_size
                                        * engine.dp_world_size
                                        * batch["input_ids"].shape[1])
                        fwd_peak, fwd_est = self._estimate(
                            compiled, n_params, tokens_micro)
                    finally:
                        engine.destroy()
                    est_cache[est_key] = (fwd_peak, fwd_est, n_params)
            except Exception as e:  # compile/shape failures prune the candidate
                res.status = "compile-failed"
                logger.debug(f"autotune candidate failed: {cfg}: {e}")
                self._append_ledger(res)
                continue
            # the lowering covers fwd+bwd only; optimizer residency and the
            # offload transfer tax are added analytically so offload twins
            # differ where it matters (peak memory, per-step time)
            res.peak_bytes = fwd_peak + self._opt_state_bytes(n_params, cfg)
            res.est_time = fwd_est + self._offload_penalty(n_params, cfg)
            # 1.15 margin over device memory: even after the alias
            # subtraction the analysis over-counts vs true buffer assignment
            # (on-chip calibration 2026-08-01). On TPU a genuinely-over
            # candidate still fails its measure-time compile cleanly (static
            # buffer assignment) and is recorded as measure-failed.
            if res.peak_bytes > self.device_memory * 1.15:
                res.status = "pruned-oom"
                self._append_ledger(res)
                continue
            res.status = "estimated"
            self._append_ledger(res)
        if n_resumed:
            log_dist(f"autotune: resumed {n_resumed}/{len(cands)} candidates "
                     f"from {self._ledger_path()}", ranks=[0])

        def global_time(r):
            # time per GLOBAL batch: the lowering is one micro step, so a
            # small-micro/high-gas candidate must pay its accumulation factor
            return r.est_time * max(
                r.config.get("gradient_accumulation_steps", 1), 1)

        def measure(res):
            # drop the previous candidates' executables/buffers first — dozens
            # of live compiled engines on an emulated many-device CPU platform
            # starve the scheduler (observed as spurious collective aborts)
            import gc

            gc.collect()
            jax.clear_caches()
            engine = self._build_engine(res.config)
            try:
                tokens = (engine.micro_batch_size * engine.dp_world_size
                          * batch["input_ids"].shape[1]
                          * engine.gradient_accumulation_steps_)
                sub = {k: v[: engine.micro_batch_size * engine.dp_world_size]
                       for k, v in batch.items()}
                engine.train_batch(batch=sub)  # compile+warm
                jax.block_until_ready(engine.params)
                t0 = time.perf_counter()
                for _ in range(measure_steps):
                    engine.train_batch(batch=sub)
                jax.block_until_ready(engine.params)
                dt = (time.perf_counter() - t0) / measure_steps
                res.measured_tokens_per_s = tokens / dt
                res.status = "measured"
                self._append_ledger(res)  # updated row; last write wins on resume
            finally:
                # destroy on the failure path too: a measure-failed candidate
                # must not pin its buffers for every candidate after it
                engine.destroy()

        def measure_safe(res):
            """True iff the candidate measured. A candidate that slipped the
            (margin-loosened) prune and fails its measure-time compile must
            cost one row, not the whole tune."""
            try:
                measure(res)
                return True
            except Exception as e:
                res.status = "measure-failed"
                logger.debug(f"autotune measure failed: {res.config}: {e}")
                self._append_ledger(res)
                return False

        live = [r for r in results if r.status in ("estimated", "measured")]
        live.sort(key=global_time)
        # walk the ranking until measured_topk candidates actually measured —
        # a measure failure must not burn one of the k slots, or a few
        # over-margin candidates at the top could leave the cost model fitting
        # on one point (or none)
        n_measured = 0
        for res in live:
            if n_measured >= measured_topk:
                break
            if res.status == "measured" or measure_safe(res):
                n_measured += 1

        # -- model-based exploration (reference tuner/model_based_tuner.py +
        # tuner/cost_model.py: fit a cost model over observed runs, use it to
        # decide what else is worth measuring). Roofline flavor: the observed
        # measured/predicted ratio recalibrates est_time; any unmeasured
        # candidate whose RECALIBRATED estimate beats the measured best gets
        # measured too (bounded by explore_topk) — the prior ranking measured
        # the wrong k exactly when this set is non-empty.
        # calibrate ONLY on the deterministic top-k set: folding exploration-
        # measured rows back in would shift the median on every resumed run,
        # promoting new candidates each time (non-idempotent resume)
        measured_now = [r for r in live
                        if r.status == "measured"
                        and r.measured_tokens_per_s > 0][:measured_topk]
        if self.model_based and measured_now:
            tokens_g = {id(r): (r.config["train_batch_size"]
                                * batch["input_ids"].shape[1])
                        for r in results}
            ratio, promoted = self._cost_model_promote(
                live, measured_now, tokens_g, global_time)
            self.calibration_ = ratio
            if promoted:
                log_dist(
                    f"autotune: cost model (x{ratio:.2f} calibration) "
                    f"promotes {len(promoted)} candidate(s) past the measured "
                    f"best; measuring up to {self.explore_topk}", ranks=[0])
            for res in promoted[:self.explore_topk]:
                measure_safe(res)

        measured = [r for r in results if r.status == "measured"]
        # never fall back to a candidate whose measure just failed (its
        # status mutated out of "estimated"): emitting it as best_config
        # would hand the user a config that already OOMed once
        viable = [r for r in live if r.status in ("estimated", "measured")]
        best = max(measured, key=lambda r: r.measured_tokens_per_s) \
            if measured else (viable[0] if viable else None)
        if best is None:
            raise RuntimeError("autotune: no viable candidate")
        log_dist(f"autotune: best {best.row()}", ranks=[0])
        # emit a config initialize() fully consumes: remat travels as the
        # engine's gradient_checkpointing flag (engine.py sets module remat)
        out = {k: v for k, v in best.config.items() if not k.startswith("_")}
        out["gradient_checkpointing"] = best.config.get("_remat") is not None
        if self.results_dir:
            import os

            with open(os.path.join(self.results_dir, "best_config.json"),
                      "w") as f:
                json.dump(out, f, indent=1)
        return out, results

    @staticmethod
    def _cost_model_promote(live, measured_now, tokens_g, global_time):
        """The fitted cost model: median measured/predicted ratio over the
        observed runs, then the unmeasured candidates it predicts beat the
        measured best, fastest-predicted first. Pure so it's testable."""
        ratios = sorted(
            (tokens_g[id(r)] / r.measured_tokens_per_s) / global_time(r)
            for r in measured_now if global_time(r) > 0)
        if not ratios:
            # cost_analysis gave no flops/bytes (est_time 0): nothing to fit
            return None, []
        ratio = ratios[len(ratios) // 2]
        best_t = min(tokens_g[id(r)] / r.measured_tokens_per_s
                     for r in measured_now)
        promoted = [r for r in live if r.status == "estimated"
                    and global_time(r) * ratio < best_t]
        promoted.sort(key=global_time)
        return ratio, promoted

    @staticmethod
    def dump(results, path):
        with open(path, "w") as f:
            json.dump([r.row() for r in results], f, indent=1)
