"""Config autotuner: sweep mesh shape x micro-batch x ZeRO stage x remat.

Reference: ``deepspeed/autotuning/`` (2.7k LoC — ``autotuner.py:404 tune``,
``tuner/{index_based,model_based}_tuner.py``, ``tuner/cost_model.py``,
experiment ``scheduler.py``): the reference launches whole training jobs per
config and fits a cost model over the results. On TPU the compiler replaces
most of that machinery:

1. **compile-prune**: every candidate's train step is jit-lowered; XLA's
   ``memory_analysis`` gives exact peak memory per candidate WITHOUT running a
   step, so OOM configs are discarded for free (the reference has to crash a
   job to learn this);
2. **cost-model rank**: ``cost_analysis`` flops/bytes -> a roofline time
   estimate orders the survivors;
3. **measure**: only the top-k candidates run real timed steps.

Emits the winning config as plain JSON (the reference's
``autotuning_results/`` contract).
"""

import dataclasses
import itertools
import json
import time

import numpy as np

from ..utils.logging import log_dist, logger


@dataclasses.dataclass
class TuneResult:
    config: dict
    peak_bytes: int = -1
    est_time: float = -1.0
    measured_tokens_per_s: float = -1.0
    status: str = "pending"  # pruned-oom | compile-failed | estimated | measured

    def row(self):
        return {
            "mesh": self.config.get("mesh"),
            "micro": self.config.get("train_micro_batch_size_per_gpu"),
            "zero": self.config.get("zero_optimization", {}).get("stage"),
            "remat": self.config.get("_remat"),
            "peak_gb": round(self.peak_bytes / 1e9, 3) if self.peak_bytes >= 0 else None,
            "est_ms": round(self.est_time * 1e3, 2) if self.est_time >= 0 else None,
            "tok_s": round(self.measured_tokens_per_s, 1)
            if self.measured_tokens_per_s >= 0 else None,
            "status": self.status,
        }


def _factor_meshes(n_devices, axes=("data", "model")):
    """All 2-axis factorizations of the device count."""
    out = []
    for model in range(1, n_devices + 1):
        if n_devices % model == 0:
            out.append({"data": n_devices // model, "model": model})
    return out


class Autotuner:
    """Sweep-and-measure over engine configs for a given model + batch shape.

    ``model_factory``: () -> model (fresh per candidate; engines own their
    params). ``base_config``: the user's config; tuned keys are overwritten.
    """

    def __init__(self, model_factory, base_config, *, device_memory_bytes=None,
                 peak_flops=None, hbm_bw=None):
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.device_memory = device_memory_bytes or self._detect_memory()
        # roofline constants for the estimate (defaults: v5e-ish)
        self.peak_flops = peak_flops or 100e12
        self.hbm_bw = hbm_bw or 6e11

    @staticmethod
    def _detect_memory():
        from ..accelerator import get_accelerator

        limit = get_accelerator().total_memory()
        return limit or 12 * 2 ** 30  # conservative when the backend won't say

    # ------------------------------------------------------------------
    def search_space(self, n_devices, global_batch):
        zero_stages = [0, 1, 2, 3]
        remats = ["minimal", None]
        micros = [m for m in (1, 2, 4, 8, 16)
                  if global_batch % (m * 1) == 0]
        meshes = _factor_meshes(n_devices)
        cands = []
        for mesh, zero, remat, micro in itertools.product(
                meshes, zero_stages, remats, micros):
            dp = mesh["data"]
            if global_batch % (micro * dp):
                continue
            cfg = dict(self.base_config)
            cfg["mesh"] = mesh
            cfg["zero_optimization"] = {"stage": zero}
            cfg["train_batch_size"] = global_batch
            cfg["train_micro_batch_size_per_gpu"] = micro
            cfg.pop("gradient_accumulation_steps", None)
            cfg["_remat"] = remat
            cands.append(cfg)
        return cands

    # ------------------------------------------------------------------
    def _build_engine(self, cfg):
        import deepspeed_tpu

        model = self.model_factory()
        if hasattr(model, "config"):
            model.config.remat = cfg.get("_remat") is not None
            if cfg.get("_remat"):
                model.config.remat_policy = cfg["_remat"]
        clean = {k: v for k, v in cfg.items() if not k.startswith("_")}
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=clean)
        return engine

    def _lower_step(self, engine, batch):
        """Lower+compile the fused fwd_bwd for analysis (the step's hot path)."""
        import jax
        import jax.numpy as jnp

        engine._build_fwd_bwd()
        sharded = engine._shard_batch(
            {k: v[: engine.micro_batch_size * engine.dp_world_size]
             for k, v in batch.items()})
        rng = jax.random.PRNGKey(0)
        lowered = engine._fwd_bwd_fn.lower(
            engine.params, sharded, jnp.asarray(1.0, jnp.float32), rng)
        return lowered.compile(), sharded, rng

    def _estimate(self, compiled):
        mem = compiled.memory_analysis()
        peak = (mem.temp_size_in_bytes + mem.argument_size_in_bytes +
                mem.output_size_in_bytes)
        cost = compiled.cost_analysis() or {}
        flops = cost.get("flops", 0.0)
        bytes_ = cost.get("bytes accessed", 0.0)
        est = max(flops / self.peak_flops, bytes_ / self.hbm_bw)
        return peak, est

    # ------------------------------------------------------------------
    def tune(self, batch, *, measured_topk=3, measure_steps=3, max_candidates=None):
        """Returns (best_config, [TuneResult...]). ``batch`` must cover the
        largest global micro-batch in the space."""
        import jax

        n_devices = len(jax.devices())
        global_batch = self.base_config.get("train_batch_size") \
            or batch["input_ids"].shape[0]
        cands = self.search_space(n_devices, global_batch)
        if max_candidates:
            cands = cands[:max_candidates]
        results = []
        for cfg in cands:
            res = TuneResult(config=cfg)
            results.append(res)
            try:
                engine = self._build_engine(cfg)
                compiled, _, _ = self._lower_step(engine, batch)
                res.peak_bytes, res.est_time = self._estimate(compiled)
            except Exception as e:  # compile/shape failures prune the candidate
                res.status = "compile-failed"
                logger.debug(f"autotune candidate failed: {cfg}: {e}")
                continue
            if res.peak_bytes > self.device_memory:
                res.status = "pruned-oom"
                continue
            res.status = "estimated"

        engine = None  # drop the last estimation-phase engine before measuring
        live = [r for r in results if r.status == "estimated"]
        live.sort(key=lambda r: r.est_time)
        for res in live[:measured_topk]:
            # drop the previous candidates' executables/buffers first — dozens
            # of live compiled engines on an emulated many-device CPU platform
            # starve the scheduler (observed as spurious collective aborts)
            import gc

            gc.collect()
            jax.clear_caches()
            engine = self._build_engine(res.config)
            tokens = (engine.micro_batch_size * engine.dp_world_size
                      * batch["input_ids"].shape[1]
                      * engine.gradient_accumulation_steps_)
            sub = {k: v[: engine.micro_batch_size * engine.dp_world_size]
                   for k, v in batch.items()}
            engine.train_batch(batch=sub)  # compile+warm
            jax.block_until_ready(engine.params)
            t0 = time.perf_counter()
            for _ in range(measure_steps):
                engine.train_batch(batch=sub)
            jax.block_until_ready(engine.params)
            dt = (time.perf_counter() - t0) / measure_steps
            res.measured_tokens_per_s = tokens / dt
            res.status = "measured"
            del engine

        measured = [r for r in results if r.status == "measured"]
        best = max(measured, key=lambda r: r.measured_tokens_per_s) \
            if measured else (live[0] if live else None)
        if best is None:
            raise RuntimeError("autotune: no viable candidate")
        log_dist(f"autotune: best {best.row()}", ranks=[0])
        # emit a config initialize() fully consumes: remat travels as the
        # engine's gradient_checkpointing flag (engine.py sets module remat)
        out = {k: v for k, v in best.config.items() if not k.startswith("_")}
        out["gradient_checkpointing"] = best.config.get("_remat") is not None
        return out, results

    @staticmethod
    def dump(results, path):
        with open(path, "w") as f:
            json.dump([r.row() for r in results], f, indent=1)
