from .optimizers import Adam, Adagrad, Lamb, SGD, TPUOptimizer, get_optimizer, OPTIMIZERS
from .lr_schedules import (
    WarmupLR,
    WarmupDecayLR,
    OneCycle,
    LRRangeTest,
    get_lr_schedule,
    SCHEDULES,
)
from .loss_scaler import (
    make_scaler_state,
    check_overflow,
    count_nonfinite,
    update_scale,
    scale_loss,
    unscale_grads,
    global_grad_norm,
    clip_grads_by_global_norm,
)

__all__ = [
    "Adam",
    "Adagrad",
    "Lamb",
    "SGD",
    "TPUOptimizer",
    "get_optimizer",
    "OPTIMIZERS",
    "WarmupLR",
    "WarmupDecayLR",
    "OneCycle",
    "LRRangeTest",
    "get_lr_schedule",
    "SCHEDULES",
    "make_scaler_state",
    "check_overflow",
    "count_nonfinite",
    "update_scale",
    "scale_loss",
    "unscale_grads",
    "global_grad_norm",
    "clip_grads_by_global_norm",
]
from .flash_attention import flash_attention  # noqa: E402

__all__.append("flash_attention")
