"""Memory-efficient attention.

Replaces the reference's fused attention kernels (``csrc/transformer/softmax_kernels.cu``
for training, ``csrc/transformer/inference/csrc/softmax.cu`` "softmax_context" for
inference). Two implementations behind one signature:

- ``flash_attention``: online-softmax attention, chunked over the KV axis with
  ``lax.scan`` so the [batch, heads, q, kv] score matrix is never materialized —
  O(seq) memory like FlashAttention. Pure XLA; runs anywhere.
- ``pallas_flash_attention`` (``ops/pallas/flash_attention.py``): the hand-tiled TPU
  kernel used when available; same semantics.

Inputs q,k,v: [batch, seq, heads, head_dim]; returns the same layout.
"""

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def parse_block_spec(spec):
    """Parse a "bq x bkv[: bq_bwd x bkv_bwd]" tile-size string (the
    BENCH_FLASH_BLOCKS / BENCH_BLOCKS knob shared by bench.py and
    tools/bench_attention.py). Returns (bq, bkv, bq_bwd, bkv_bwd) with the
    backward pair None when omitted."""
    fwd, _, bwd = spec.partition(":")
    bq, bkv = (int(x) for x in fwd.split("x"))
    if bwd:
        bqb, bkvb = (int(x) for x in bwd.split("x"))
    else:
        bqb = bkvb = None
    return bq, bkv, bqb, bkvb


def flash_attention(q, k, v, causal=True, scale=None, block_size=512,
                    block_q=None, block_kv=None, block_q_bwd=None,
                    block_kv_bwd=None):
    """Online-softmax attention, scanned over KV blocks.

    For each query block the running (max, sum, acc) triple is updated per KV chunk —
    the same recurrence the FlashAttention kernel uses, expressed as ``lax.scan`` so
    XLA keeps the working set in registers/VMEM. ``block_*`` override the
    Pallas kernel's tile sizes (tuning knobs; ignored by the XLA fallback).
    """
    if _tpu_kernel_eligible(q, k):
        from .pallas.flash_attention import pallas_flash_attention

        s_q, s_kv = q.shape[1], k.shape[1]
        if block_q is None and block_kv is None and s_kv <= 1024:
            # Measured default (2026-08-01 on-chip sweep, PERF.md): at
            # s_kv <= 1024 a SINGLE kv block per grid step drops the
            # online-softmax rescale loop entirely — fwd 512x{s_kv} +
            # bwd 512x{s_kv} tiles beat the generic 256x512/256x256 by
            # +22% end-to-end training throughput at the bench shape.
            # Longer sequences keep the generic tiles until the 2k-8k tile
            # sweep (bench_attention) lands.
            block_q = min(512, s_q)
            block_kv = s_kv
            block_q_bwd = block_q_bwd or min(512, s_q)
            block_kv_bwd = block_kv_bwd or s_kv
        return pallas_flash_attention(q, k, v, causal=causal, scale=scale,
                                      block_q=min(block_q or 256, s_q),
                                      block_kv=min(block_kv or 512, s_kv),
                                      block_q_bwd=block_q_bwd,
                                      block_kv_bwd=block_kv_bwd)
    return _chunked_attention(q, k, v, causal=causal, scale=scale,
                              block_size=block_size)


def _tpu_kernel_eligible(q, k):
    """One gate for every Pallas dispatcher (in-repo and official kernels):
    TPU backend + 128-aligned sequence lengths. Shared so the impls can't
    drift — a rule change here applies to both."""
    return (jax.default_backend() == "tpu"
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0)


def jax_flash_attention(q, k, v, causal=True, scale=None):
    """The official JAX TPU flash kernel behind our [b, s, h, d] signature.

    ``jax.experimental.pallas.ops.tpu.flash_attention`` is the
    production-tuned Mosaic kernel (fwd + custom-vjp bwd, [b, h, s, d]
    layout). Exposed as ``attention_impl="jax_flash"`` so the bench can
    compare it head-to-head with the in-repo kernel and XLA attention —
    whichever wins becomes the recommended default. Off-TPU (CPU tests)
    this falls back to the same chunked-XLA path as ``flash_attention``,
    so parity tests exercise identical semantics.

    Known integration asymmetry: under ``remat`` the in-repo kernel saves
    its lse residual by checkpoint name ("minimal" policy), so its backward
    skips the forward recompute; the official kernel's residuals are
    internal to its custom vjp and get recomputed. Sweep rows measure that
    real user-facing cost; ``tools/bench_attention.py`` (no remat) is the
    raw kernel-vs-kernel comparison.
    """
    if _tpu_kernel_eligible(q, k):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _jax_flash)

        sm_scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
        out = _jax_flash(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=sm_scale)
        return out.transpose(0, 2, 1, 3)
    return _chunked_attention(q, k, v, causal=causal, scale=scale)


def _chunked_attention(q, k, v, causal=True, scale=None, block_size=512):
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block = min(block_size, s_kv)
    if s_kv % block:
        block = s_kv  # fall back to one chunk for ragged sizes
    n_blocks = s_kv // block

    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # [b,h,q,d]
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)

    k_blocks = kf.reshape(b, h, n_blocks, block, d).transpose(2, 0, 1, 3, 4)
    v_blocks = vf.reshape(b, h, n_blocks, block, d).transpose(2, 0, 1, 3, 4)

    q_idx = jnp.arange(s_q)[:, None] + (s_kv - s_q)  # align causal window to kv end

    def body(carry, inputs):
        m, l, acc = carry
        (kb, vb, blk) = inputs
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)  # [b,h,q,block]
        if causal:
            kv_idx = blk * block + jnp.arange(block)[None, :]
            mask = kv_idx <= q_idx  # [q, block]
            logits = jnp.where(mask[None, None], logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, s_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    acc0 = jnp.zeros((b, h, s_q, d), jnp.float32)
    blks = jnp.arange(n_blocks)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (k_blocks, v_blocks, blks))

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
