"""Block-sparsity patterns for sparse attention.

Reference: ``deepspeed/ops/sparse_attention/sparsity_config.py`` — the
Dense/Fixed/Variable/BigBird/BSLongformer pattern family (``:63/:95/:239/:411/
:546``). The reference materializes per-head torch layout tensors consumed by
Triton SDD/DSD kernels; here a pattern is pure data — a numpy block mask
``[n_q_blocks, n_kv_blocks]`` — consumed by the Pallas kernel's scalar-prefetch
block lists (``ops/pallas/block_sparse_attention.py``). Patterns follow the
published semantics (Sparse Transformers fixed pattern, BigBird, Longformer),
re-derived from the papers.
"""

import dataclasses

import numpy as np


@dataclasses.dataclass
class SparsityConfig:
    """Base: block size in tokens; subclasses fill ``make_layout``."""

    block: int = 128

    def make_layout(self, n_q_blocks, n_kv_blocks):
        raise NotImplementedError

    def layout_for(self, seq_q, seq_kv, causal=True):
        if seq_q % self.block or seq_kv % self.block:
            raise ValueError(
                f"sequence ({seq_q},{seq_kv}) must divide block {self.block}")
        nq, nkv = seq_q // self.block, seq_kv // self.block
        layout = self.make_layout(nq, nkv).astype(bool)
        if causal:
            # block-level causal reachability (block diag aligned to kv end)
            off = nkv - nq
            q_idx = np.arange(nq)[:, None]
            kv_idx = np.arange(nkv)[None, :]
            layout &= kv_idx <= q_idx + off
            # the diagonal block is always attendable (self-attention)
            layout[q_idx[:, 0], np.clip(q_idx[:, 0] + off, 0, nkv - 1)] = True
        if not layout.any(axis=1).all():
            raise ValueError("sparsity layout leaves a query block with no "
                             "attendable kv block")
        return layout


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    """All blocks attend all blocks (reference ``Dense:63``)."""

    def make_layout(self, nq, nkv):
        return np.ones((nq, nkv), bool)


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers fixed pattern (reference ``Fixed:95``): blocks are
    grouped in local stretches of ``num_local_blocks``; a query attends its own
    stretch plus the last ``num_global_blocks`` ("summary") blocks of every
    earlier stretch."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, nq, nkv):
        layout = np.zeros((nq, nkv), bool)
        off = nkv - nq
        for qb in range(nq):
            pos = qb + off  # this block's index on the kv axis
            stretch = pos // self.num_local_blocks
            lo = stretch * self.num_local_blocks
            hi = min(lo + self.num_local_blocks, nkv)
            layout[qb, lo:hi] = True
            for s in range(stretch):
                end = (s + 1) * self.num_local_blocks
                layout[qb, max(0, end - self.num_global_blocks):end] = True
        return layout


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference ``BigBird:411``): sliding window + global first
    blocks (rows and columns) + per-row random blocks."""

    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    num_random_blocks: int = 1
    seed: int = 0

    def make_layout(self, nq, nkv):
        layout = np.zeros((nq, nkv), bool)
        off = nkv - nq
        w = self.num_sliding_window_blocks // 2
        rng = np.random.RandomState(self.seed)
        for qb in range(nq):
            pos = qb + off
            layout[qb, max(0, pos - w):min(nkv, pos + w + 1)] = True
            layout[qb, :self.num_global_blocks] = True  # global columns
            if self.num_random_blocks and nkv > 1:
                picks = rng.choice(nkv, size=min(self.num_random_blocks, nkv),
                                   replace=False)
                layout[qb, picks] = True
        layout[:self.num_global_blocks, :] = True  # global rows attend all
        return layout


@dataclasses.dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (reference ``BSLongformer:546``): sliding window
    + designated global block indices that attend/are attended everywhere."""

    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)

    def make_layout(self, nq, nkv):
        layout = np.zeros((nq, nkv), bool)
        off = nkv - nq
        w = self.num_sliding_window_blocks // 2
        for qb in range(nq):
            pos = qb + off
            layout[qb, max(0, pos - w):min(nkv, pos + w + 1)] = True
        for g in self.global_block_indices:
            if g < nkv:
                layout[:, g] = True
            if g < nq:
                layout[g, :] = True
        return layout


@dataclasses.dataclass
class VariableSparsityConfig(SparsityConfig):
    """Reference ``Variable:239``: custom local window sizes (a list of block
    counts, cycled over stretches) plus global first blocks."""

    local_window_blocks: tuple = (4,)
    num_global_blocks: int = 1

    def make_layout(self, nq, nkv):
        layout = np.zeros((nq, nkv), bool)
        off = nkv - nq
        # stretch boundaries from the cycled window sizes
        bounds = [0]
        i = 0
        while bounds[-1] < nkv:
            bounds.append(bounds[-1]
                          + self.local_window_blocks[i % len(self.local_window_blocks)])
            i += 1
        for qb in range(nq):
            pos = qb + off
            for lo, hi in zip(bounds[:-1], bounds[1:]):
                if lo <= pos < hi:
                    layout[qb, lo:min(hi, nkv)] = True
                    break
        layout[:, :self.num_global_blocks] = True
        return layout
