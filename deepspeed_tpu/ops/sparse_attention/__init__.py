"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``):
pattern configs + the splash-style Pallas kernel."""

from .sparsity_config import (  # noqa: F401
    SparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    VariableSparsityConfig,
)
from ..pallas.block_sparse_attention import BlockSparseAttention  # noqa: F401
