"""Groupwise quantization ops (reference ``csrc/quantization/`` via
``QuantizerBuilder``, ``op_builder/quantizer.py:9``).

Symmetric groupwise int8/int4 (de)quantization as jittable XLA functions — the
CUDA kernels' job (memory-bound elementwise + per-group reductions) is exactly
what XLA fuses well on TPU. Used by the compression package (MoQ-style weight
quantization) and the inference engine's weight-quant path.
"""

import jax
import jax.numpy as jnp


def _group_reshape(x, group_size):
    flat = x.reshape(-1)
    n = flat.shape[0]
    if group_size <= 0 or n % group_size:
        # one group per row-ish fallback: single group
        group_size = n
    return flat.reshape(n // group_size, group_size), x.shape, group_size


def quantize(x, bits=8, group_size=64):
    """Symmetric groupwise quantization.

    Returns (q int8, scale f32 per group, meta) with
    ``dequantize(q, scale, meta)`` restoring the original shape.
    """
    grouped, shape, group_size = _group_reshape(jnp.asarray(x, jnp.float32), group_size)
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(grouped), axis=1, keepdims=True)
    scale = absmax / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(grouped / safe), -qmax - 1, qmax).astype(jnp.int8)
    meta = {"shape": shape, "bits": bits, "group_size": group_size}
    return q, scale.astype(jnp.float32), meta


def dequantize(q, scale, meta):
    out = q.astype(jnp.float32) * scale
    return out.reshape(meta["shape"])


def fake_quantize(x, bits=8, group_size=64):
    """Quantize-dequantize in one jittable op (reference ``fake_quantizer.cu``) —
    the training-time MoQ forward. Straight-through estimator for gradients."""
    def fwd(x):
        q, scale, meta = quantize(x, bits=bits, group_size=group_size)
        return dequantize(q, scale, meta).astype(x.dtype)

    @jax.custom_vjp
    def ste(x):
        return fwd(x)

    ste.defvjp(lambda x: (fwd(x), None), lambda _, g: (g,))
    return ste(x)


def quantization_error(x, bits=8, group_size=64):
    """Mean squared quantization error (used by the MoQ eigenvalue-driven schedule)."""
    q, scale, meta = quantize(x, bits=bits, group_size=group_size)
    return jnp.mean((dequantize(q, scale, meta) - jnp.asarray(x, jnp.float32)) ** 2)


def quantize_per_channel(w, bits=8, group_size=0):
    """Weight-only serving quantization: symmetric per-output-channel int8,
    optionally sub-grouped along the input dim.

    w: [..., in, out] -> (q int8 same shape, scale f32 [..., groups, 1, out]).
    ``group_size``: quantization granularity along the in-dim (0 / >= in means
    one group = plain per-channel). The dequant (q * scale) fuses into the
    consuming matmul, so the weight is READ from HBM at 8 bits — the
    bandwidth/footprint win the reference's ``GroupQuantizer`` int8 path gets
    from its dequant kernels (``csrc/.../dequantize.cu``).
    """
    w = jnp.asarray(w)
    in_dim = w.shape[-2]
    if group_size <= 0 or group_size >= in_dim or in_dim % group_size:
        group_size = in_dim
    groups = in_dim // group_size
    lead = w.shape[:-2]
    wg = w.astype(jnp.float32).reshape(lead + (groups, group_size, w.shape[-1]))
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = absmax / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(wg / safe), -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(w.shape), scale.astype(jnp.float32)


def dequantize_per_channel(q, scale, dtype):
    """Inverse of ``quantize_per_channel`` in the consuming dtype."""
    groups = scale.shape[-3]
    lead = q.shape[:-2]
    in_dim, out = q.shape[-2], q.shape[-1]
    qg = q.astype(dtype).reshape(lead + (groups, in_dim // groups, out))
    return (qg * scale.astype(dtype)).reshape(q.shape)


def pack_int4(q):
    """int4 values (int8 storage in [-8, 7], [..., in, out], even in-dim) ->
    one uint8 per PAIR of in-dim weights ([..., in/2, out]) — the true 4-bit
    HBM footprint the reference's int4 kernels get (``quantize.cu``)."""
    if q.shape[-2] % 2:
        raise ValueError(f"int4 packing needs an even in-dim, got {q.shape}")
    u = (q.astype(jnp.int16) + 8).astype(jnp.uint8)  # [0, 15]
    lo = u[..., 0::2, :]
    hi = u[..., 1::2, :]
    return ((hi << 4) | lo).astype(jnp.uint8)


def unpack_int4(packed):
    """[..., in/2, out] uint8 -> int4-valued int8 [..., in, out]."""
    lo = (packed & jnp.uint8(0xF)).astype(jnp.int8) - 8
    hi = (packed >> 4).astype(jnp.int8) - 8
    pairs = jnp.stack([lo, hi], axis=-2)  # [..., in/2, 2, out]
    return pairs.reshape(packed.shape[:-2]
                         + (packed.shape[-2] * 2, packed.shape[-1]))
