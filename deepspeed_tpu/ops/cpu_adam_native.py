"""ctypes binding for the native host-side Adam/Adagrad (csrc/adam/cpu_adam.cpp).

The reference's CPUAdamBuilder loads an AVX Adam extension for ZeRO-Offload
(``deepspeed/ops/adam/cpu_adam.py``); this is the same role over numpy fp32
buffers, used by ``runtime/offload.py`` when the offloaded optimizer is
adam/adamw/adagrad. Falls back cleanly (``available()`` False) when g++ is
missing or the build fails.
"""

import ctypes

import numpy as np

from ..utils.logging import logger
from .op_builder.builder import CPUAdamBuilder

_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is None and not _load_failed:
        try:
            lib = CPUAdamBuilder().load()
            lib.ds_cpu_adam_step.argtypes = [
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_float,
            ]
            lib.ds_cpu_adagrad_step.argtypes = [
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_float,
            ]
            _lib = lib
        except Exception as e:
            logger.warning(f"native cpu_adam unavailable ({e}); "
                           f"offload falls back to the jitted host step")
            _load_failed = True
    return _lib


def available():
    return _load() is not None


def _fptr(a):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native cpu_adam library unavailable (build failed or g++ "
            "missing); check available() and fall back to the jitted step")
    return lib


def adam_step_inplace(p, g, m, v, *, step, lr, betas, eps, weight_decay,
                      adamw_mode, bias_correction, decay, grad_scale=1.0):
    """In-place fused Adam(W) on fp32 numpy leaves (p/m/v mutated)."""
    _require().ds_cpu_adam_step(
        _fptr(p), _fptr(g), _fptr(m), _fptr(v), p.size, int(step), float(lr),
        float(betas[0]), float(betas[1]), float(eps), float(weight_decay),
        int(bool(adamw_mode)), int(bool(bias_correction)), int(bool(decay)),
        float(grad_scale))


def adagrad_step_inplace(p, g, s, *, lr, eps, weight_decay, decay,
                         grad_scale=1.0):
    """In-place Adagrad on fp32 numpy leaves (p/s mutated)."""
    _require().ds_cpu_adagrad_step(
        _fptr(p), _fptr(g), _fptr(s), p.size, float(lr), float(eps),
        float(weight_decay), int(bool(decay)), float(grad_scale))
