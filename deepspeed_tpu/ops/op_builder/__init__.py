from .builder import OpBuilder, AsyncIOBuilder

# named registry consumed by the accelerator's op-builder dispatch
# (reference ``op_builder/__init__.py`` builder_closure / ALL_OPS)
ALL_OPS = {"async_io": AsyncIOBuilder}

__all__ = ["OpBuilder", "AsyncIOBuilder", "ALL_OPS"]
