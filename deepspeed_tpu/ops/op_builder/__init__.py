from .builder import OpBuilder, AsyncIOBuilder

__all__ = ["OpBuilder", "AsyncIOBuilder"]
