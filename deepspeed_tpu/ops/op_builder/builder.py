"""JIT builder for native (C++) ops.

TPU-native equivalent of the reference's op-builder system (``op_builder/builder.py:438
load`` / ``:451`` JIT path): compile a C++ source into a shared library on first use,
cache it under the build directory, load through ctypes. No torch extension machinery —
the native surface here is host-side (async IO), so a plain `g++ -shared` suffices.
"""

import ctypes
import hashlib
import os
import subprocess

from ...utils.logging import logger

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
_DEFAULT_BUILD_DIR = os.environ.get(
    "DS_TPU_BUILD_DIR", os.path.join(_REPO_ROOT, "build", "ops"))


class OpBuilder:
    """Compile-and-load for one native op library."""

    NAME = None
    SOURCES = ()          # repo-relative C++ sources
    EXTRA_FLAGS = ()

    def __init__(self, build_dir=None):
        self.build_dir = build_dir or _DEFAULT_BUILD_DIR
        self._lib = None

    def sources(self):
        return [os.path.join(_REPO_ROOT, s) for s in self.SOURCES]

    def is_compatible(self):
        """Reference builder compatibility probe (``op_builder/builder.py``)."""
        from shutil import which

        return which("g++") is not None

    def _signature(self):
        import platform

        h = hashlib.sha256()
        for src in self.sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.EXTRA_FLAGS).encode())
        # -march=native binaries are host-ISA-specific, and dlopen does NOT
        # validate ISA extensions (a foreign cache would SIGILL at call time,
        # not rebuild) — key the cache on the host's arch + feature flags
        h.update(platform.machine().encode())
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    # x86 spells it "flags", aarch64 "Features"
                    if line.startswith(("flags", "Features")):
                        h.update(line.encode())
                        break
        except OSError:
            pass
        return h.hexdigest()[:16]

    def lib_path(self):
        return os.path.join(self.build_dir, f"{self.NAME}_{self._signature()}.so")

    def build(self):
        path = self.lib_path()
        if os.path.exists(path):
            return path
        os.makedirs(self.build_dir, exist_ok=True)
        # per-process temp name so concurrent builders never interleave writes;
        # os.replace makes the final publish atomic
        tmp = f"{path}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               *self.EXTRA_FLAGS, *self.sources(), "-o", tmp]
        logger.info(f"Building native op {self.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return path

    def load(self):
        """Build if needed and dlopen (reference ``XxxBuilder().load()``)."""
        if self._lib is None:
            if not self.is_compatible():
                raise RuntimeError(
                    f"Native op {self.NAME} requires g++, which is unavailable")
            path = self.build()
            try:
                self._lib = ctypes.CDLL(path)
            except OSError:
                # stale/foreign-arch cached .so (e.g. built on another platform):
                # rebuild from source once
                logger.warning(f"cached {path} failed to dlopen; rebuilding")
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass  # a concurrent process already cleaned it up
                self._lib = ctypes.CDLL(self.build())
        return self._lib


class AsyncIOBuilder(OpBuilder):
    """Reference ``op_builder/async_io.py:12`` AsyncIOBuilder -> csrc/aio."""

    NAME = "ds_aio"
    SOURCES = ("csrc/aio/ds_aio.cpp",)


class CPUAdamBuilder(OpBuilder):
    """Reference ``op_builder/cpu_adam.py:10`` CPUAdamBuilder -> csrc/adam.

    -march=native + OpenMP: the simd pragma loops compile to the host's widest
    vector ISA (the reference's hand-written simd.h intrinsics), and the
    parallel-for spreads a leaf across cores. The cache key includes the host
    arch + cpu flags (see _signature) so a binary built elsewhere is never
    loaded. No -ffast-math: linking it pulls crtfastmath.o into the .so, and
    dlopen would then set FTZ/DAZ process-wide, silently changing float
    semantics for every host computation in the process.
    """

    NAME = "ds_cpu_adam"
    SOURCES = ("csrc/adam/cpu_adam.cpp",)
    EXTRA_FLAGS = ("-fopenmp", "-march=native")
