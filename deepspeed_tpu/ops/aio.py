"""Python surface of the async-IO native op (reference ``deepspeed/ops/aio`` +
``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` AsyncIOHandle).

``AsyncIOHandle`` submits numpy-buffer reads/writes to the C++ thread pool and
returns request handles; ``wait``/``wait_all`` block on completion. Powers the
NVMe optimizer/param swappers (``runtime/offload.py``).
"""

import ctypes

import numpy as np

from .op_builder import AsyncIOBuilder


class AsyncIOHandle:
    def __init__(self, n_threads=4):
        self._lib = AsyncIOBuilder().load()
        self._lib.ds_aio_create.restype = ctypes.c_void_p
        self._lib.ds_aio_create.argtypes = [ctypes.c_int]
        self._lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        self._lib.ds_aio_submit_write.restype = ctypes.c_int64
        self._lib.ds_aio_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64]
        self._lib.ds_aio_submit_read.restype = ctypes.c_int64
        self._lib.ds_aio_submit_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64]
        self._lib.ds_aio_wait.restype = ctypes.c_int
        self._lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._lib.ds_aio_wait_all.restype = ctypes.c_int
        self._lib.ds_aio_wait_all.argtypes = [ctypes.c_void_p]
        self._h = self._lib.ds_aio_create(int(n_threads))
        # keep buffers alive until their request completes
        self._pinned = {}

    def write(self, path, array, offset=0):
        """Submit an async write of a C-contiguous numpy array; returns request id."""
        arr = np.ascontiguousarray(array)
        req = self._lib.ds_aio_submit_write(
            self._h, str(path).encode(), arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes, int(offset))
        self._pinned[req] = arr
        return req

    def read(self, path, array, offset=0):
        """Submit an async read into a preallocated C-contiguous numpy array."""
        if not array.flags["C_CONTIGUOUS"] or not array.flags["WRITEABLE"]:
            raise ValueError("read target must be a writable C-contiguous array")
        req = self._lib.ds_aio_submit_read(
            self._h, str(path).encode(), array.ctypes.data_as(ctypes.c_void_p),
            array.nbytes, int(offset))
        self._pinned[req] = array
        return req

    def wait(self, req):
        rc = self._lib.ds_aio_wait(self._h, int(req))
        self._pinned.pop(req, None)
        if rc != 0:
            raise OSError(-rc, f"async io request {req} failed")
        return rc

    def wait_all(self):
        rc = self._lib.ds_aio_wait_all(self._h)
        self._pinned.clear()
        if rc != 0:
            raise OSError(-rc, "async io batch failed")
        return rc

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_destroy(self._h)
                self._h = None
        except Exception:
            pass
