"""Block-sparse flash attention (splash-style) Pallas TPU kernels.

The TPU-native replacement for the reference's Triton block-sparse attention
(``deepspeed/ops/sparse_attention/matmul.py:17`` SDD/DSD kernels +
``softmax.py``): instead of sparse-matmul primitives over a materialized
layout, the *grid itself* is sparse — per q block, a scalar-prefetched list of
active kv block indices drives the BlockSpec index_map, so inactive blocks
cost neither DMA nor compute (the same idea as the public splash-attention
kernel). The dense flash kernel (``flash_attention.py``) is the special case
"every block active".

Static preprocessing turns a block mask [n_q_blocks, n_kv_blocks] (from
``ops/sparse_attention/sparsity_config.py``) into padded active-block lists
for the forward/dq direction and their transpose for dkv. The online-softmax
math and the FlashAttention-2 backward split are identical to the dense
kernel's.
"""

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams as _CompilerParams

NEG_INF = -1e30
LANES = 128


def _active_lists(layout):
    """bool [nq, nkv] -> (idx [nq, max_a] int32 padded w/ last, counts [nq])."""
    nq, _ = layout.shape
    lists = [np.nonzero(layout[j])[0] for j in range(nq)]
    counts = np.asarray([len(l) for l in lists], np.int32)
    max_a = max(1, int(counts.max()))
    idx = np.zeros((nq, max_a), np.int32)
    for j, l in enumerate(lists):
        if len(l) == 0:
            continue
        idx[j, :len(l)] = l
        idx[j, len(l):] = l[-1]
    return idx, counts, max_a


def _mask_tile(s, this_kv, j, block_q, block_kv, q_offset):
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(
        this_kv * block_kv + col <= j * block_q + row + q_offset, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward: grid (b*h, n_qb, max_active); kv block index read from prefetch
# ---------------------------------------------------------------------------
def _fwd_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_kv,
                q_offset):
    j = pl.program_id(1)
    a = pl.program_id(2)
    this_kv = idx_ref[j, a]
    n_act = cnt_ref[j]

    @pl.when(a == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        on_diag = this_kv * block_kv + block_kv - 1 > j * block_q + q_offset
    else:
        on_diag = jnp.asarray(False)
    run = a < n_act

    def step(masked):
        # bf16 dot inputs + fp32 accumulation (MXU native); upcasting tiles to
        # fp32 before the dot runs fp32xfp32 matmuls at a fraction of bf16
        # throughput (same fix as flash_attention.py)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if masked:
            s = _mask_tile(s, this_kv, j, block_q, block_kv, q_offset)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(run & jnp.logical_not(on_diag))
    def _full():
        step(False)

    if causal:
        @pl.when(run & on_diag)
        def _diag():
            step(True)

    @pl.when(a == n_act - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                      lse_ref.shape[1:])


# ---------------------------------------------------------------------------
# backward dQ: same sparse grid as forward
# ---------------------------------------------------------------------------
def _dq_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
               dq_ref, dq_scr, delta_scr, *, scale, causal, block_q, block_kv,
               q_offset):
    j = pl.program_id(1)
    a = pl.program_id(2)
    this_kv = idx_ref[j, a]
    n_act = cnt_ref[j]

    @pl.when(a == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        delta = jnp.sum(o * do, axis=-1, keepdims=True)
        delta_scr[...] = jnp.broadcast_to(delta, delta_scr.shape)

    if causal:
        on_diag = this_kv * block_kv + block_kv - 1 > j * block_q + q_offset
    else:
        on_diag = jnp.asarray(False)
    run = a < n_act

    def step(masked):
        # bf16 dot inputs + fp32 accumulation (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if masked:
            s = _mask_tile(s, this_kv, j, block_q, block_kv, q_offset)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_scr[:, :1]) * scale
        dq_scr[...] += jnp.dot(ds.astype(k.dtype), k,
                               preferred_element_type=jnp.float32)

    @pl.when(run & jnp.logical_not(on_diag))
    def _full():
        step(False)

    if causal:
        @pl.when(run & on_diag)
        def _diag():
            step(True)

    @pl.when(a == n_act - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward dK/dV: grid (b*h, n_kvb, max_active_q); q block index prefetched
# ---------------------------------------------------------------------------
def _dkv_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                block_kv, q_offset):
    jkv = pl.program_id(1)
    a = pl.program_id(2)
    this_q = idx_ref[jkv, a]
    n_act = cnt_ref[jkv]

    @pl.when(a == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        on_diag = jkv * block_kv + block_kv - 1 > this_q * block_q + q_offset
    else:
        on_diag = jnp.asarray(False)
    run = a < n_act

    def step(masked):
        # bf16 dot inputs + fp32 accumulation (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0]
        delta = jnp.sum(o * do.astype(jnp.float32), axis=-1, keepdims=True)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if masked:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(
                jkv * block_kv + col <= this_q * block_q + row + q_offset,
                s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(run & jnp.logical_not(on_diag))
    def _full():
        step(False)

    if causal:
        @pl.when(run & on_diag)
        def _diag():
            step(True)

    @pl.when(a == n_act - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    # a kv block no query attends (possible in the layout transpose) still owns
    # an output tile — zero it or it's garbage
    @pl.when((a == 0) & (n_act == 0))
    def _untouched():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])


# ---------------------------------------------------------------------------
# host-side wrapper
# ---------------------------------------------------------------------------
class BlockSparseAttention:
    """Callable sparse attention for a fixed (seq, pattern, block) shape —
    the reference's ``SparseSelfAttention`` role (sparse_self_attention.py),
    with the layout preprocessing done once at construction."""

    def __init__(self, config, seq_q, seq_kv=None, causal=True, scale=None,
                 interpret=False):
        seq_kv = seq_kv or seq_q
        self.block = config.block
        self.causal = causal
        self.interpret = interpret
        self.scale = scale
        layout = config.layout_for(seq_q, seq_kv, causal=causal)
        self.layout = layout
        self.density = float(layout.mean())
        self._fwd_idx, self._fwd_cnt, self._max_a = _active_lists(layout)
        self._bwd_idx, self._bwd_cnt, self._max_aq = _active_lists(layout.T)
        self.seq_q, self.seq_kv = seq_q, seq_kv

        @jax.custom_vjp
        def attend(q, k, v):
            out, _ = self._forward(q, k, v)
            return out

        def fwd(q, k, v):
            out, lse = self._forward(q, k, v)
            # named residuals so remat policies ("minimal") can save them —
            # without the lse name the backward re-runs the whole forward
            # kernel per layer just to regenerate it (same fix as
            # ops/pallas/flash_attention.py _vjp_fwd)
            out = checkpoint_name(out, "attn_out")
            lse = checkpoint_name(lse, "attn_lse")
            return out, (q, k, v, out, lse)

        def bwd(res, g):
            return self._backward(*res, g)

        attend.defvjp(fwd, bwd)
        self._attend = attend

    def __call__(self, q, k, v):
        """q: [b, s_q, h, d]; k/v: [b, s_kv, h, d] -> [b, s_q, h, d]."""
        return self._attend(q, k, v)

    # -- shared plumbing ----------------------------------------------------
    def _prep(self, x, s):
        b, _, h, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    def _forward(self, q, k, v):
        b, s_q, h, d = q.shape
        s_kv = k.shape[1]
        assert s_q == self.seq_q and s_kv == self.seq_kv, \
            (s_q, s_kv, self.seq_q, self.seq_kv)
        blk = self.block
        scale = self.scale if self.scale is not None else 1.0 / math.sqrt(d)
        qr, kr, vr = (self._prep(q, s_q), self._prep(k, s_kv),
                      self._prep(v, s_kv))
        nq = s_q // blk
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * h, nq, self._max_a),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda i, j, a, idx, cnt: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk, d),
                             lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk, d),
                             lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d), lambda i, j, a, idx, cnt: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk, LANES),
                             lambda i, j, a, idx, cnt: (i, j, 0),
                             memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((blk, LANES), jnp.float32),
                pltpu.VMEM((blk, LANES), jnp.float32),
                pltpu.VMEM((blk, d), jnp.float32),
            ],
        )
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, causal=self.causal,
                              block_q=blk, block_kv=blk, q_offset=s_kv - s_q),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, s_q, LANES), jnp.float32),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=self.interpret,
        )(jnp.asarray(self._fwd_idx), jnp.asarray(self._fwd_cnt), qr, kr, vr)
        out = out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        return out, lse[..., :1]

    def _backward(self, q, k, v, out, lse, g):
        b, s_q, h, d = q.shape
        s_kv = k.shape[1]
        blk = self.block
        scale = self.scale if self.scale is not None else 1.0 / math.sqrt(d)
        lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))
        qr, kr, vr = (self._prep(q, s_q), self._prep(k, s_kv),
                      self._prep(v, s_kv))
        orr, gr = self._prep(out, s_q), self._prep(g, s_q)
        nq, nkv = s_q // blk, s_kv // blk
        common = dict(scale=scale, causal=self.causal, block_q=blk,
                      block_kv=blk, q_offset=s_kv - s_q)

        dq = pl.pallas_call(
            functools.partial(_dq_kernel, **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b * h, nq, self._max_a),
                in_specs=[
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, LANES),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((1, blk, d),
                                       lambda i, j, a, idx, cnt: (i, j, 0),
                                       memory_space=pltpu.VMEM),
                scratch_shapes=[
                    pltpu.VMEM((blk, d), jnp.float32),
                    pltpu.VMEM((blk, LANES), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=self.interpret,
        )(jnp.asarray(self._fwd_idx), jnp.asarray(self._fwd_cnt),
          qr, kr, vr, orr, gr, lse)

        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b * h, nkv, self._max_aq),
                in_specs=[
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, LANES),
                                 lambda i, j, a, idx, cnt: (i, idx[j, a], 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=[
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, blk, d),
                                 lambda i, j, a, idx, cnt: (i, j, 0),
                                 memory_space=pltpu.VMEM),
                ],
                scratch_shapes=[
                    pltpu.VMEM((blk, d), jnp.float32),
                    pltpu.VMEM((blk, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b * h, s_kv, d), k.dtype),
                jax.ShapeDtypeStruct((b * h, s_kv, d), v.dtype),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=self.interpret,
        )(jnp.asarray(self._bwd_idx), jnp.asarray(self._bwd_cnt),
          qr, kr, vr, orr, gr, lse)

        to4 = lambda x, s: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)
        return to4(dq, s_q), to4(dk, s_kv), to4(dv, s_kv)
