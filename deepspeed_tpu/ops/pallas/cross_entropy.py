"""Pallas TPU streaming LM-head cross-entropy (forward kernel).

The XLA fused CE (``ops/cross_entropy.py``) never materializes the full
[tokens, vocab] logit matrix, but each vocab CHUNK's logits still round-trip
HBM between the head GEMM and the logsumexp fusion (~5 GB of traffic at the
350M bench shape). Here the chunk tile lives in VMEM: grid
(token_tiles, vocab_tiles) with vocab innermost, the x tile resident across
the vocab sweep (same-index revisit, no refetch), and the online
(m, s, label-logit) triple in VMEM scratch — logits never touch HBM at all.

Forward-only by design: the backward's cost is two big MXU GEMMs (dx, dE)
that XLA already runs at peak; re-deriving them in Pallas would force an
extra recompute of the score GEMM per kernel (the flash dq/dkv split) and
LOSE flops. ``pallas_ce_loss`` plugs into ``fused_cross_entropy``'s
custom-vjp as an alternate forward via ``impl="pallas"``.

Reference role: ``csrc/transformer/softmax_kernels.cu`` (fused softmax-CE
for training) applied to the LM head, where TPU HBM bandwidth matters most.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, _fit_block


def _ce_fwd_kernel(labels_ref, x_ref, e_ref, b_ref, lse_ref, lab_ref,
                   m_scr, s_scr, lab_scr, *, block_v, n_vb, vocab, scale_bias):
    """Grid (token_tiles, vocab_tiles); vocab innermost ("arbitrary").

    labels_ref: [bt, LANES] int32 (label broadcast across lanes);
    x_ref: [bt, d]; e_ref: [block_v, d]; b_ref: [1, block_v] fp32 logit bias
    (zeros when the head has none); lse_ref/lab_ref: [bt, LANES] fp32 out.
    """
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        s_scr[...] = jnp.zeros_like(s_scr)
        lab_scr[...] = jnp.zeros_like(lab_scr)

    x = x_ref[...]
    e = e_ref[...]
    logits = jax.lax.dot_general(
        x, e, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bt, block_v]
    if scale_bias:
        logits = logits + b_ref[0][None, :]
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    if vocab % block_v:
        # padded (fake-vocab) columns must not contribute
        logits = jnp.where(col < vocab, logits, NEG_INF)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    s_scr[...] = (s_scr[...] * jnp.exp(m_prev - m_new)
                  + jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True))
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    # label logit: one-hot select within this chunk (labels broadcast on
    # lanes); exactly one chunk hits per row, the rest contribute 0
    lab = labels_ref[:, :1]  # [bt, 1]
    hit = col == lab  # [bt, block_v]
    lab_scr[...] = lab_scr[...] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, logits, 0.0), axis=-1, keepdims=True),
        lab_scr.shape)

    @pl.when(vb == n_vb - 1)
    def _finalize():
        lse_ref[...] = m_scr[...] + jnp.log(jnp.maximum(s_scr[...], 1e-30))
        lab_ref[...] = lab_scr[...]


def pallas_ce_forward(x, emb, labels, bias=None, *, block_t=256, block_v=512,
                      interpret=False):
    """Returns (lse [tokens] fp32, label_logit [tokens] fp32).

    x: [tokens, d] (compute dtype); emb: [V, d]; labels: [tokens] int32 —
    callers mask ignore_index themselves (pass any in-range id; the returned
    label logit for masked rows is unused).
    """
    tokens, d = x.shape
    vocab = emb.shape[0]
    # compute-dtype GEMM inputs like the XLA path (e_c.astype(x.dtype)):
    # fp32 master embeddings would stream at double width AND make the fwd
    # lse diverge from the backward's recomputed compute-dtype logits
    emb = emb.astype(x.dtype)
    bt = _fit_block(block_t, tokens)
    bv = min(block_v, vocab)
    n_vb = -(-vocab // bv)
    padded = n_vb * bv
    if padded != vocab:
        emb = jnp.pad(emb, ((0, padded - vocab), (0, 0)))
    bias_arr = jnp.zeros((1, padded), jnp.float32) if bias is None \
        else jnp.pad(bias.astype(jnp.float32), (0, padded - vocab))[None, :]

    labels_b = jnp.broadcast_to(labels.astype(jnp.int32)[:, None],
                                (tokens, LANES))

    kernel = functools.partial(
        _ce_fwd_kernel, block_v=bv, n_vb=n_vb, vocab=vocab,
        scale_bias=bias is not None)
    lse, lab = pl.pallas_call(
        kernel,
        grid=(tokens // bt, n_vb),
        in_specs=[
            pl.BlockSpec((bt, LANES), lambda t, vb: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, d), lambda t, vb: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, d), lambda t, vb: (vb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bv), lambda t, vb: (0, vb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bt, LANES), lambda t, vb: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, LANES), lambda t, vb: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tokens, LANES), jnp.float32),
            jax.ShapeDtypeStruct((tokens, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bt, LANES), jnp.float32),
            pltpu.VMEM((bt, LANES), jnp.float32),
            pltpu.VMEM((bt, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(labels_b, x, emb, bias_arr)
    return lse[:, 0], lab[:, 0]
