"""Pallas TPU flash-attention (forward) kernel.

The TPU-native replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, inference ``softmax.cu`` "softmax_context"):
online-softmax attention tiled over query blocks (grid) and key/value blocks
(in-kernel fori_loop), fp32 accumulators in VMEM scratch, causal blocks skipped
entirely.

Training backward uses the chunked-XLA recompute path via ``custom_vjp`` (memory-safe
and differentiable everywhere); the forward kernel is the latency/throughput-critical
piece for both training fwd and inference prefill.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_kv, kv_len,
                q_offset, block_q):
    """One (batch*head, q_block) program; loops over kv blocks.

    Block shapes: q_ref/o_ref [1, block_q, d]; k_ref/v_ref [1, kv_len, d].
    """
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
    d = q.shape[-1]

    n_kv_total = kv_len // block_kv
    if causal:
        # last kv position any row in this q block may attend to (global index)
        last_kv = qb * block_q + (block_q - 1) + q_offset
        n_kv = jnp.minimum((last_kv // block_kv) + 1, n_kv_total)
    else:
        n_kv = n_kv_total

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_kv, block_kv), :].astype(jnp.float32)
        s_ij = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv]
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            q_pos = qb * block_q + row + q_offset
            kv_pos = i * block_kv + col
            s_ij = jnp.where(kv_pos <= q_pos, s_ij, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_ij, axis=-1, keepdims=True))
        p = jnp.exp(s_ij - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret):
    """q,k,v: [b, s, h, d] -> out [b, s, h, d]."""
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    bq = min(block_q, s_q)
    bkv = min(block_kv, s_kv)
    if s_q % bq or s_kv % bkv:
        raise ValueError(f"seq lengths ({s_q},{s_kv}) must divide blocks ({bq},{bkv})")

    # [b, s, h, d] -> [b*h, s, d]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, s_kv, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, s_kv, d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_kv=bkv, kv_len=s_kv,
        q_offset=s_kv - s_q, block_q=bq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_kv, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_kv, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def pallas_flash_attention(q, k, v, causal=True, scale=None, block_q=256,
                           block_kv=256, interpret=False):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret)


def _vjp_fwd(q, k, v, causal, scale, block_q, block_kv, interpret):
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret)
    return out, (q, k, v)


def _vjp_bwd(causal, scale, block_q, block_kv, interpret, residuals, g):
    """Backward via recompute through the chunked-XLA path (same semantics)."""
    from ..flash_attention import _chunked_attention

    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal=causal, scale=scale,
                                              block_size=block_kv),
        q, k, v,
    )
    return vjp(g)


pallas_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
