"""Pallas TPU flash-attention: tiled forward AND backward kernels.

The TPU-native replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu`` for training, the inference
"softmax_context" kernels in ``csrc/transformer/inference/csrc/softmax.cu``):
online-softmax attention tiled over query blocks x key/value blocks, fp32
accumulators in VMEM scratch, causally-skippable kv blocks.

Layout notes (the TPU way):
- grid = (batch*heads, q_blocks, kv_blocks) with the kv dimension innermost and
  "arbitrary" semantics: the (m, l, acc) running triple lives in VMEM scratch and
  persists across the kv iterations of one q block; K/V HBM->VMEM streaming is
  handled by the BlockSpec pipeline (double-buffered by Pallas), so VMEM holds
  only one K/V block at a time — long sequences never blow VMEM.
- the row statistics (m/l/lse/delta) are kept broadcast across a 128-lane minor
  dim: TPU vregs are (8, 128), so a [block_q, 1] column would relayout on every
  use; [block_q, 128] broadcast is the idiomatic layout (same trick as the
  reference's warp-level row reductions, just vectorized).
- backward = two kernels, the standard FlashAttention-2 split: dKV (grid over kv
  blocks, loop over q) and dQ (grid over q blocks, loop over kv), each
  recomputing the probability tile from (q, k, lse) so nothing O(s^2) is ever
  materialized. ``delta = rowsum(dO * O)`` is computed in-kernel at the first
  visit instead of as a separate XLA pass.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams as _CompilerParams

NEG_INF = -1e30
LANES = 128


def _fit_block(requested, seq):
    """Largest block in [1, requested] that divides seq (backward clamps block
    sizes, which must never silently truncate the grid)."""
    b = max(1, min(requested, seq))
    while seq % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale, causal, block_q,
                block_kv, q_offset, n_kvb, emit_lse):
    if emit_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        # last kv block any row of this q block attends to; diagonal blocks mask
        limit = (j * block_q + block_q - 1 + q_offset) // block_kv
        last = jnp.minimum(limit, n_kvb - 1)
        on_diag = kb * block_kv + block_kv - 1 > j * block_q + q_offset
        run_full = jnp.logical_and(kb <= limit, jnp.logical_not(on_diag))
        run_diag = jnp.logical_and(kb <= limit, on_diag)
    else:
        last = n_kvb - 1
        run_full = jnp.asarray(True)
        run_diag = jnp.asarray(False)

    def step(masked):
        # dots take the INPUT dtype (bf16 in training) with fp32 accumulation —
        # the MXU's native mode. Upcasting tiles to fp32 before the dot forces
        # fp32xfp32 matmuls at a fraction of bf16 throughput (measured: the
        # whole kernel lost to plain XLA attention until this was fixed).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bkv] fp32
        if masked:
            row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(kb * block_kv + col <= j * block_q + row + q_offset,
                          s, NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(run_full)
    def _full():
        step(False)

    if causal:
        @pl.when(run_diag)
        def _diag():
            step(True)

    @pl.when(kb == last)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if emit_lse:
            lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                          lse_ref.shape[1:])


def _fwd_kernel_single(q_ref, k_ref, v_ref, o_ref, *rest, scale, causal,
                       block_q, block_kv, q_offset, emit_lse):
    """One kv block = the whole sequence: plain softmax, NO online-softmax
    machinery. The (m, l, acc) scratch triple, its zero-init pass, the
    correction multiplies, and the acc read-modify-write all drop out — this
    is the configuration the measured 0.4157 winner runs (512x1024 tiles at
    seq 1024), so the bookkeeping it pays is pure overhead."""
    lse_ref = rest[0] if emit_lse else None
    j = pl.program_id(1)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, bkv] fp32
    if causal:
        # always mask: the elementwise select on [bq, bkv] is noise next to
        # the dot, and skipping it for fully-below-diagonal q blocks would
        # reintroduce the two-branch dispatch this kernel exists to shed
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(col <= j * block_q + row + q_offset, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    acc = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    if emit_lse:
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape[1:])


def _flash_fwd_single(qr, kr, vr, bh, s_q, s_kv, d, causal, scale, bq,
                      interpret, need_lse, out_dtype):
    """pallas_call wrapper for the single-kv-block kernel (2D grid, no
    scratch). kv/v blocks are the full sequence."""
    kernel = functools.partial(
        _fwd_kernel_single, scale=scale, causal=causal, block_q=bq,
        block_kv=s_kv, q_offset=s_kv - s_q, emit_lse=need_lse,
    )
    out_specs = [pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((bh, s_q, d), out_dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, bq, LANES), lambda i, j: (i, j, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((bh, s_q, LANES), jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=(bh, s_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_kv, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_kv, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(qr, kr, vr)


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
               need_lse=False):
    """q,k,v: [b, s, h, d] -> out [b, s, h, d] (+ lse [b*h, s_q, 128] fp32)."""
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    if causal and s_q > s_kv:
        # the causal offset math assumes queries align to the END of the kv
        # sequence (q_offset >= 0); with s_q > s_kv early q blocks would have
        # no finalize step and return uninitialized output
        raise ValueError(
            f"causal flash attention requires s_q <= s_kv, got s_q={s_q} "
            f"s_kv={s_kv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    # clamp to the largest divisor <= requested — a non-dividing request (e.g.
    # default 512 at seq 640) must degrade, not crash at trace time
    bq = _fit_block(block_q, s_q)
    bkv = _fit_block(block_kv, s_kv)
    n_kvb = s_kv // bkv

    # [b, s, h, d] -> [b*h, s, d]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, s_kv, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, s_kv, d)

    if n_kvb == 1:
        res = _flash_fwd_single(qr, kr, vr, b * h, s_q, s_kv, d, causal,
                                scale, bq, interpret, need_lse, q.dtype)
        out = res[0].reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        if need_lse:
            return out, res[1][..., :1]
        return out

    q_offset = s_kv - s_q
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_kv=bkv,
        q_offset=q_offset, n_kvb=n_kvb, emit_lse=need_lse,
    )

    if causal:
        # above-diagonal iterations are compute-skipped (pl.when) but Pallas
        # would still DMA whatever block the index_map names — clamp them to
        # the diagonal block so the revisit-dedup skips the fetch (at long
        # seq this halves K/V HBM traffic)
        def kv_index(i, j, kb):
            # outer maximum: with s_q > s_kv (unsupported, but reachable via
            # the generic entry point) q_offset < 0 makes the clamp limit
            # negative — keep the index in range instead of handing the DMA
            # an out-of-range block
            return (i, jnp.maximum(
                jnp.minimum(kb, (j * bq + bq - 1 + q_offset) // bkv), 0), 0)
    else:
        def kv_index(i, j, kb):
            return (i, kb, 0)

    out_specs = [pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, bq, LANES), lambda i, j, kb: (i, j, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((b * h, s_q, LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // bq, n_kvb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bkv, d), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bkv, d), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = res[0].reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    if need_lse:
        # keep only one lane as the residual: the [.., LANES] broadcast is the
        # in-kernel layout, not worth 128x the HBM between fwd and bwd
        return out, res[1][..., :1]
    return out


# ---------------------------------------------------------------------------
# backward: dQ kernel — grid (b*h, q_blocks, kv_blocks)
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
               dq_scr, delta_scr, *, scale, causal, block_q, block_kv,
               q_offset, n_kvb):
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        delta = jnp.sum(o * do, axis=-1, keepdims=True)  # [bq, 1]
        delta_scr[...] = jnp.broadcast_to(delta, delta_scr.shape)

    if causal:
        limit = (j * block_q + block_q - 1 + q_offset) // block_kv
        last = jnp.minimum(limit, n_kvb - 1)
        on_diag = kb * block_kv + block_kv - 1 > j * block_q + q_offset
        run_full = jnp.logical_and(kb <= limit, jnp.logical_not(on_diag))
        run_diag = jnp.logical_and(kb <= limit, on_diag)
    else:
        last = n_kvb - 1
        run_full = jnp.asarray(True)
        run_diag = jnp.asarray(False)

    def step(masked):
        # bf16 dot inputs, fp32 accumulation (see _fwd_kernel.step)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv] fp32
        if masked:
            row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(kb * block_kv + col <= j * block_q + row + q_offset,
                          s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv] fp32
        ds = p * (dp - delta_scr[:, :1]) * scale
        dq_scr[...] += jnp.dot(ds.astype(k.dtype), k,
                               preferred_element_type=jnp.float32)

    @pl.when(run_full)
    def _full():
        step(False)

    if causal:
        @pl.when(run_diag)
        def _diag():
            step(True)

    @pl.when(kb == last)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dq_kernel_single(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
                      scale, causal, block_q, block_kv, q_offset):
    """dQ with one kv block = whole sequence: single pass, no accumulation
    scratch (same rationale as _fwd_kernel_single — this is the measured
    winner's bwd tile shape)."""
    j = pl.program_id(1)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    o = o_ref[0].astype(jnp.float32)
    delta = jnp.sum(o * do.astype(jnp.float32), axis=-1, keepdims=True)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(col <= j * block_q + row + q_offset, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, :1])
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dq_ref[0] = jnp.dot(ds.astype(k.dtype), k,
                        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _dkv_kernel_single(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                       dv_ref, *, scale, causal, block_q, block_kv, q_offset):
    """dK/dV with one q block = the whole query range (the maxq bwd tile):
    single pass, no accumulation scratch."""
    jkv = pl.program_id(1)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    o = o_ref[0].astype(jnp.float32)
    do = do_ref[0]
    delta = jnp.sum(o * do.astype(jnp.float32), axis=-1, keepdims=True)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(jkv * block_kv + col <= row + q_offset, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, :1])
    dv_ref[0] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dk_ref[0] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dK/dV kernel — grid (b*h, kv_blocks, q_blocks)
# ---------------------------------------------------------------------------
def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, scale, causal, block_q, block_kv,
                q_offset, n_qb):
    jkv = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        # q block contributes iff its last row reaches this kv block's start
        contrib = qb * block_q + block_q - 1 + q_offset >= jkv * block_kv
        # diagonal iff the kv block's end passes the q block's first row
        on_diag = jkv * block_kv + block_kv - 1 > qb * block_q + q_offset
        run_full = jnp.logical_and(contrib, jnp.logical_not(on_diag))
        run_diag = jnp.logical_and(contrib, on_diag)
    else:
        run_full = jnp.asarray(True)
        run_diag = jnp.asarray(False)

    def step(masked):
        # bf16 dot inputs, fp32 accumulation (see _fwd_kernel.step)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0]
        delta = jnp.sum(o * do.astype(jnp.float32), axis=-1,
                        keepdims=True)  # [bq, 1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv] fp32
        if masked:
            row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(jkv * block_kv + col <= qb * block_q + row + q_offset,
                          s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])  # [bq, bkv] fp32
        # dV += P^T dO
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # [bq, bkv] fp32
        # dK += dS^T Q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    @pl.when(run_full)
    def _full():
        step(False)

    if causal:
        @pl.when(run_diag)
        def _diag():
            step(True)

    @pl.when(qb == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_kv, interpret):
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    if causal and s_q > s_kv:
        raise ValueError(
            f"causal flash attention requires s_q <= s_kv, got s_q={s_q} "
            f"s_kv={s_kv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    bq = _fit_block(block_q, s_q)
    bkv = _fit_block(block_kv, s_kv)
    n_qb, n_kvb = s_q // bq, s_kv // bkv
    q_offset = s_kv - s_q

    to3 = lambda x, s: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qr, kr, vr = to3(q, s_q), to3(k, s_kv), to3(v, s_kv)
    orr, gr = to3(out, s_q), to3(g, s_q)

    if causal:
        # clamp skipped above-diagonal fetches to the diagonal block so the
        # revisit-dedup skips their DMA (see _flash_fwd)
        def kv_index(i, j, kb):
            # outer maximum: with s_q > s_kv (unsupported, but reachable via
            # the generic entry point) q_offset < 0 makes the clamp limit
            # negative — keep the index in range instead of handing the DMA
            # an out-of-range block
            return (i, jnp.maximum(
                jnp.minimum(kb, (j * bq + bq - 1 + q_offset) // bkv), 0), 0)

        def q_index_dkv(i, jkv, qb):
            # dkv grid iterates q blocks; blocks before the kv block's causal
            # reach are compute-skipped — clamp their fetch to the first
            # contributing q block
            return (i, jnp.maximum(qb, (jkv * bkv - q_offset) // bq), 0)
    else:
        def kv_index(i, j, kb):
            return (i, kb, 0)

        def q_index_dkv(i, jkv, qb):
            return (i, qb, 0)

    if n_kvb == 1:
        # single-pass dQ (no accumulation scratch): the winner's bwd shape
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_single, scale=scale, causal=causal,
                              block_q=bq, block_kv=bkv, q_offset=q_offset),
            grid=(b * h, n_qb),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, LANES), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel")
            ),
            interpret=interpret,
        )(qr, kr, vr, orr, gr, lse)
    else:
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal, block_q=bq,
                              block_kv=bkv, q_offset=q_offset, n_kvb=n_kvb),
            grid=(b * h, n_qb, n_kvb),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), kv_index, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), kv_index, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, LANES), lambda i, j, kb: (i, j, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            ),
            interpret=interpret,
        )(qr, kr, vr, orr, gr, lse)

    if n_qb == 1:
        # single-pass dK/dV (no accumulation scratch): the maxq bwd shape
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_single, scale=scale, causal=causal,
                              block_q=bq, block_kv=bkv, q_offset=q_offset),
            grid=(b * h, n_kvb),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, LANES), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, s_kv, d), k.dtype),
                jax.ShapeDtypeStruct((b * h, s_kv, d), v.dtype),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel")
            ),
            interpret=interpret,
        )(qr, kr, vr, orr, gr, lse)
    else:
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal,
                              block_q=bq, block_kv=bkv, q_offset=q_offset,
                              n_qb=n_qb),
            grid=(b * h, n_kvb, n_qb),
            in_specs=[
                pl.BlockSpec((1, bq, d), q_index_dkv, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j, qb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j, qb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), q_index_dkv, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), q_index_dkv, memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, LANES), q_index_dkv, memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bkv, d), lambda i, j, qb: (i, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bkv, d), lambda i, j, qb: (i, j, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, s_kv, d), k.dtype),
                jax.ShapeDtypeStruct((b * h, s_kv, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bkv, d), jnp.float32),
                pltpu.VMEM((bkv, d), jnp.float32),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            ),
            interpret=interpret,
        )(qr, kr, vr, orr, gr, lse)

    to4 = lambda x, s: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return to4(dq, s_q), to4(dk, s_kv), to4(dv, s_kv)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def pallas_flash_attention(q, k, v, causal=True, scale=None, block_q=256,
                           block_kv=512, interpret=False, block_q_bwd=None,
                           block_kv_bwd=None):
    """block_q/block_kv tile the forward; block_q_bwd/block_kv_bwd the two
    backward kernels (default: forward blocks clamped to 256 — the bwd holds
    more live tiles per step, so its sweet spot is smaller)."""
    return _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret)


def _vjp_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
             block_q_bwd, block_kv_bwd):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
                          need_lse=True)
    # Names for remat policies: saving "attn_out"+"attn_lse" (models' "minimal"
    # policy) makes the backward's residuals fully available — without the lse
    # name the checkpoint recompute must RE-RUN the whole forward kernel just
    # to regenerate the [tokens, 1] lse.
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _vjp_bwd(causal, scale, block_q, block_kv, interpret, block_q_bwd,
             block_kv_bwd, residuals, g):
    q, k, v, out, lse = residuals
    lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))
    return _flash_bwd(q, k, v, out, lse, g, causal, scale,
                      block_q_bwd or min(block_q, 256),
                      block_kv_bwd or min(block_kv, 256), interpret)


pallas_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
