"""Split-KV paged flash-decode Pallas kernel: decode attention that walks the
block table IN-KERNEL.

The serving decode hot path is 1 query row per slot against a long KV window
stored as a paged pool (``serving/kv_pool.py``: ``[n_blocks, block_size, kvh,
dh]`` physical blocks + a per-slot block table). The gather path
(``models/decoding.py:_paged_view``) materializes a dense per-slot view of
that pool per layer — correct and compile-once, but pure transient HBM
traffic: every decode step writes (and immediately re-reads) an
``[S, NB*bs, kvh, dh]`` tensor whose only purpose is to look like the dense
cache. This kernel deletes that view: the block-table indirection happens in
the BlockSpec index map (scalar-prefetched table + cursors, so the DMA
engine chases ``table[s, j]`` directly), and the online-softmax inner loop
masks each slot's ragged cursor in-register — DeepSpeed-Inference's fused
decode attention play (arXiv:2207.00032), TPU-native.

Shape/structure notes (the TPU way, same idioms as
``ops/pallas/flash_attention.py``):

- grid = (slots, kv_heads, kv_splits, blocks_per_split). The kv-head
  dimension rides the grid so GQA costs nothing: each cell runs the
  ``n_heads // kv_heads`` query rows of ONE kv head against that head's
  slice of the pool — the q block is ``[hq, dh]``, dense in the MXU.
- split-KV: each of the ``kv_splits`` grid cells owns a contiguous run of
  table columns and produces a PARTIAL (max, sum, accumulator) triple; the
  partials combine outside the kernel (a tiny ``[S, kvh, splits, hq]``
  fp32 reduction) — the FlashDecoding shape, so long contexts parallelize
  across the split grid instead of serializing one slot's whole window.
- the freshly-projected k/v row of the CURRENT token never touches the
  pool before attention: it folds into the softmax during the combine, in
  compute dtype — exactly the value the gather path attends (the fresh row
  is written to the view pre-attention there), so int8 pools see the same
  unquantized current row on both paths and the writeback stays where it
  was.
- per-slot cursor masks: a slot's valid pool window is positions
  ``[0, pos)`` (ragged mid-block cursors included); blocks wholly past the
  cursor are compute-skipped (``pl.when``) and their DMA lands on whatever
  block id the table holds there — freed/unbound columns hold the reserved
  GARBAGE block, so the fetch is always in-range and its values are never
  read into the softmax.
- int8 pools dequantize IN-KERNEL: the int8 payload block and its
  per-(token, head) fp32 scale stream to VMEM natively and the
  ``payload.astype(f32) * scale`` happens on the tile — elementwise ops
  identical to ``comm/collectives.py:dequantize_blockwise``, so the fused
  path reads bit-identical dequantized values, at half the pool HBM
  traffic of gathering an already-dequantized view.

Tier-1 runs this kernel under ``interpret=True`` on CPU (the same
discipline as the flash kernels' interpret tests), so correctness — ragged
cursors, GQA, alibi, int8, garbage-block exclusion — is pinned without
chips.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams

NEG_INF = -1e30
LANES = 128


def _fit_splits(requested, n_columns):
    """Largest split count in [1, requested] dividing ``n_columns`` (the
    block-table width) — a non-dividing request degrades, never crashes."""
    s = max(1, min(int(requested), n_columns))
    while n_columns % s:
        s -= 1
    return s


def fused_decode_supported(cfg, block_size, *, mp_world_size=1,
                           backend=None, kv_dtype=""):
    """Capability probe for the fused backend: ``(ok, reason)``.

    GQA, rope and alibi are supported natively (rope is applied to q/k
    before the cache, so the pool already holds post-rope keys; alibi is an
    in-kernel bias; GQA rides the grid). What is NOT:

    - banded local-attention layers (GPT-Neo style): the per-layer band
      mask isn't implemented in-kernel — the gather path stays correct;
    - on a real TPU backend, lane/sublane alignment: ``head_dim`` must fill
      the 128-lane minor dim and ``block_size`` the 8-sublane tile, a
      model-sharded mesh needs the gather path (``pallas_call`` carries no
      SPMD partitioning rule, so GSPMD would replicate the pool), and int8
      pools stay on the gather path until a chip session validates the
      per-(token, head) scale tiles' (bs, 1) layout under Mosaic — the
      probe must never approve a shape the compiler then rejects, or the
      warn-and-fall-back contract becomes a hard failure at first
      dispatch. Interpret mode (every non-TPU backend, which is how tier-1
      pins the kernel on CPU) has none of these constraints.
    """
    if cfg.local_attention_window > 0:
        return False, ("local_attention_window > 0: banded layer masks are "
                       "not implemented in the fused kernel")
    if cfg.n_heads % cfg.kv_heads:
        return False, (f"n_heads {cfg.n_heads} not a multiple of kv_heads "
                       f"{cfg.kv_heads}")
    backend = backend if backend is not None else jax.default_backend()
    if backend == "tpu":
        if cfg.head_dim % LANES:
            return False, (f"head_dim {cfg.head_dim} not a multiple of the "
                           f"{LANES}-lane minor dim (TPU)")
        if block_size % 8:
            return False, (f"kv_pool.block_size {block_size} not a multiple "
                           "of the 8-sublane tile (TPU)")
        if mp_world_size > 1:
            return False, ("tensor-parallel mesh: pallas_call has no SPMD "
                           "partitioning rule — the gather path shards the "
                           "kv-head axis instead")
        if kv_dtype == "int8":
            return False, ("kv_dtype=int8 on TPU: the in-kernel dequant's "
                           "per-(token, head) scale tiles are not yet "
                           "chip-validated under Mosaic — gather path "
                           "until a live-TPU session clears them")
    return True, ""


def _decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, *rest, scale,
                   block_size, blocks_per_split, int8, alibi,
                   m_prev_bcast):
    """One (slot, kv_head, split, block) cell: stream one physical block,
    fold it into the split's running (m, l, acc) triple, emit the partial
    at the split's last block. ``table_ref``/``pos_ref`` are the
    scalar-prefetched block table and cursors (the index maps already used
    them to aim the DMA; the body re-reads the cursor for the mask)."""
    idx = 0
    if int8:
        ks_ref, vs_ref = rest[idx], rest[idx + 1]
        idx += 2
    slopes_ref = None
    if alibi:
        slopes_ref = rest[idx]
        idx += 1
    o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = rest[idx:]

    s = pl.program_id(0)
    jb = pl.program_id(3)

    @pl.when(jb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = pos_ref[s]                       # valid pool window = [0, pos)
    sp = pl.program_id(2)
    base = (sp * blocks_per_split + jb) * block_size

    @pl.when(base < pos)
    def _step():
        # the allowlisted attention-f32 island (see sanitizer
        # ATTENTION_F32_ALLOW): QK logits and the PV accumulator run fp32
        # on purpose — softmax numerics — with narrow dot INPUTS (the
        # MXU's native mode, same as the flash kernels)
        with jax.named_scope("paged_flash_decode"):
            q = q_ref[0, 0]                # [hq, dh]
            k = k_ref[0, :, 0]             # [bs, dh]
            v = v_ref[0, :, 0]
            if int8:
                # dequantize ON the tile — elementwise-identical to
                # dequantize_blockwise (f32 payload * per-(token,head)
                # scale, then the compute-dtype cast the gather view takes)
                k = (k.astype(jnp.float32) * ks_ref[0, :, 0]).astype(q.dtype)
                v = (v.astype(jnp.float32) * vs_ref[0, :, 0]).astype(q.dtype)
            sc = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [hq, bs] f32
            col = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
            if alibi:
                # slopes * (kv_pos - cursor): the same int-difference-then-
                # fp32-multiply as the gather path's per-row alibi
                dist = (base + col - pos).astype(jnp.float32)
                sc = sc + slopes_ref[0][:, None] * dist
            sc = jnp.where(base + col < pos, sc, NEG_INF)
            m_prev = m_scr[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
            p = jnp.exp(sc - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[...] = l_scr[...] * corr + jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), l_scr.shape)
            acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jb == blocks_per_split - 1)
    def _emit():
        # partials, not normalized output: splits with no valid positions
        # emit (m=-inf, l=0, acc=0) and drop out of the combine exactly
        o_ref[0, 0, 0] = acc_scr[...]
        m_ref[0, 0, 0] = m_scr[...][:, :m_prev_bcast]
        l_ref[0, 0, 0] = l_scr[...][:, :m_prev_bcast]


def paged_flash_decode(q, k_new, v_new, kc, vc, table, pos, *, k_scale=None,
                       v_scale=None, scale=None, alibi_slopes=None,
                       kv_splits=4, interpret=None):
    """Fused paged decode attention: softmax(q·K/√d)·V for ONE query row per
    slot, where K/V live in the paged pool and the kernel walks the block
    table itself.

    - ``q``: [S, n_heads, dh] (compute dtype) — this step's query rows;
    - ``k_new``/``v_new``: [S, kvh, dh] — the freshly-projected k/v of the
      current token (NOT yet in the pool; logically at position ``pos[s]``,
      folded into the softmax in compute dtype during the combine);
    - ``kc``/``vc``: [n_blocks, block_size, kvh, dh] — one layer of the
      pool (int8 payloads when ``k_scale``/``v_scale`` [n_blocks, bs, kvh,
      1] f32 are given: dequantized in-kernel);
    - ``table``: [S, NB] int32 physical block ids (scalar-prefetched: the
      index map reads it to aim each block DMA — no dense view exists);
    - ``pos``: [S] int32 cursors; pool positions [0, pos) are attended,
      everything past the cursor (ragged mid-block tails, unbound
      garbage-block columns) is masked/skipped.

    Returns [S, n_heads, dh] in ``q.dtype``.
    """
    s_dim, n_heads, dh = q.shape
    n_blocks, block_size, kvh, _ = kc.shape
    nb_cols = table.shape[1]
    hq = n_heads // kvh
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    int8 = k_scale is not None
    alibi = alibi_slopes is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    splits = _fit_splits(kv_splits, nb_cols)
    bps = nb_cols // splits
    grid = (s_dim, kvh, splits, bps)
    qr = q.reshape(s_dim, kvh, hq, dh)

    def kv_index(s, g, sp, jb, table_ref, pos_ref):
        # THE point of the kernel: the block-table indirection lives here.
        # Unbound columns hold the reserved garbage block — always a valid
        # pool row, compute-skipped in the body.
        return (table_ref[s, sp * bps + jb], 0, g, 0)

    def q_index(s, g, sp, jb, table_ref, pos_ref):
        return (s, g, 0, 0)

    def out_index(s, g, sp, jb, table_ref, pos_ref):
        return (s, g, sp, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, hq, dh), q_index),
        pl.BlockSpec((1, block_size, 1, dh), kv_index),
        pl.BlockSpec((1, block_size, 1, dh), kv_index),
    ]
    operands = [qr, kc, vc]
    if int8:
        in_specs += [pl.BlockSpec((1, block_size, 1, 1), kv_index),
                     pl.BlockSpec((1, block_size, 1, 1), kv_index)]
        operands += [k_scale, v_scale]
    if alibi:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(kvh, hq)
        in_specs.append(pl.BlockSpec(
            (1, hq), lambda s, g, sp, jb, t, p: (g, 0)))
        operands.append(slopes)

    # the m/l partials keep a LANES-broadcast minor dim in scratch (TPU vreg
    # layout; see flash_attention.py). Interpret mode emits a single lane to
    # HBM; a real TPU emits the full broadcast — a 1-lane minor output dim
    # is a layout Mosaic tiling commonly rejects, and the probe must never
    # approve a shape the compiler then refuses
    stat_lanes = 1 if interpret else LANES
    out_shape = [
        jax.ShapeDtypeStruct((s_dim, kvh, splits, hq, dh), jnp.float32),
        jax.ShapeDtypeStruct((s_dim, kvh, splits, hq, stat_lanes),
                             jnp.float32),
        jax.ShapeDtypeStruct((s_dim, kvh, splits, hq, stat_lanes),
                             jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, 1, hq, dh), out_index),
        pl.BlockSpec((1, 1, 1, hq, stat_lanes), out_index),
        pl.BlockSpec((1, 1, 1, hq, stat_lanes), out_index),
    ]

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_size=block_size,
        blocks_per_split=bps, int8=int8, alibi=alibi,
        m_prev_bcast=stat_lanes)
    acc, m_p, l_p = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, dh), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(table, pos, *operands)

    # -- combine across the split-KV grid (tiny fp32 reduction) ------------
    m_p = m_p[..., 0]                                    # [S, kvh, sp, hq]
    l_p = l_p[..., 0]
    m_c = jnp.max(m_p, axis=2)                           # [S, kvh, hq]
    w = jnp.exp(m_p - m_c[:, :, None, :])                # empty splits -> 0
    l_c = jnp.sum(l_p * w, axis=2)
    acc_c = jnp.sum(acc * w[..., None], axis=2)          # [S, kvh, hq, dh]

    # -- fold the CURRENT token's fresh k/v row (compute dtype, position
    # pos — the row the gather path writes into the view pre-attention;
    # alibi distance is 0 there). Elementwise mul+sum, not a dot: this is
    # [S, kvh, hq] of work, VPU noise.
    qf = qr.astype(jnp.float32)
    s_new = jnp.sum(qf * k_new.astype(jnp.float32)[:, :, None, :],
                    axis=-1) * scale                     # [S, kvh, hq]
    m_t = jnp.maximum(m_c, s_new)
    corr = jnp.exp(m_c - m_t)
    w_new = jnp.exp(s_new - m_t)
    l_t = l_c * corr + w_new
    acc_t = acc_c * corr[..., None] \
        + w_new[..., None] * v_new.astype(jnp.float32)[:, :, None, :]
    out = acc_t / jnp.maximum(l_t, 1e-30)[..., None]
    return out.reshape(s_dim, n_heads, dh).astype(q.dtype)
