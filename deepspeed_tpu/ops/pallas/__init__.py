"""Pallas TPU kernels.

Shared jax-version shim: jax < 0.5 spells the Mosaic params class
``TPUCompilerParams``; newer jax renamed it ``CompilerParams``. Every kernel
module imports the resolved name from here so the next rename is a one-line
fix instead of four.
"""

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) \
    or getattr(_pltpu, "TPUCompilerParams")
