"""Pallas TPU fused dequant-matmul for weight-only int8/int4 serving.

Reference role: the inference dequant kernels
(``csrc/transformer/inference/csrc/dequantize.cu`` and the int8/int4 gemm
epilogues behind ``pt_binding.cpp``) — the CUDA answer to "never materialize
the fp16 weight". The XLA path (``models/layers.py linear_apply``) hopes the
``unpack + q * scale`` chain fuses into the consuming matmul; measured on
chip (2026-08-01 serving bench, PERF.md) it does for int8 but NOT for the
int4 nibble unpack — the stack/reshape breaks fusion, the full-size bf16
weight round-trips HBM every decode step, and int4 decode lands 3-4x SLOWER
than bf16. Here the packed bytes are what streams HBM->VMEM; the unpack,
group-scale multiply, and MXU dot all happen per-tile in VMEM:

- grid (out_tiles, k_tiles), k innermost, the [m, bn] accumulator resident
  in its output tile across the k sweep (same-index revisit, no refetch);
- int4 avoids an in-kernel row interleave with the identity
  ``y = sum_p x[2p] w[2p] + x[2p+1] w[2p+1]`` = ``x_even @ lo + x_odd @ hi``
  (lo/hi = the two nibbles of the packed byte row p, which hold exactly the
  even/odd input rows per ``ops/quantizer.py pack_int4``);
- groupwise scales (``quantize_per_channel`` layout [groups, 1, out]) are
  applied to the dequantized tile before the dot; a k-tile never straddles a
  group boundary by construction (block_k is clamped to a divisor-aligned
  size, see ``_pick_blocks``).

Forward-only by design: quantized kernels exist only on the serving path
(``inference/engine.py _quantize_weights``); nothing differentiates through
them.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams as _CompilerParams


def _int8_kernel(x_ref, q_ref, s_ref, o_ref, *, n_groups, dot_dtype):
    kb = pl.program_id(1)
    q = q_ref[...]                                   # [bk, bn] int8
    s = s_ref[...].astype(jnp.float32)               # [nG, bn]
    bk, bn = q.shape
    w = q.astype(jnp.float32).reshape(n_groups, bk // n_groups, bn)
    w = (w * s[:, None, :]).reshape(bk, bn).astype(dot_dtype)
    x = x_ref[...].astype(dot_dtype)                 # [m, bk]
    part = jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(kb == 0)
    def _init():
        o_ref[...] = part

    @pl.when(kb != 0)
    def _acc():
        o_ref[...] += part


def _int4_kernel(xe_ref, xo_ref, q_ref, s_ref, o_ref, *, n_groups, dot_dtype):
    kb = pl.program_id(1)
    u = q_ref[...]                                   # [bk2, bn] uint8
    bk2, bn = u.shape
    lo = (u & jnp.uint8(0xF)).astype(jnp.int8) - 8   # even input rows
    hi = (u >> 4).astype(jnp.int8) - 8               # odd input rows
    s = s_ref[...].astype(jnp.float32)               # [nG, bn]
    # nibble row p holds input rows 2p (lo) and 2p+1 (hi); both belong to
    # group p // (g/2), so one [nG, g/2, bn] broadcast scales either nibble
    gh = bk2 // n_groups

    def scaled(w):
        w = w.astype(jnp.float32).reshape(n_groups, gh, bn)
        return (w * s[:, None, :]).reshape(bk2, bn).astype(dot_dtype)

    xe = xe_ref[...].astype(dot_dtype)               # [m, bk2]
    xo = xo_ref[...].astype(dot_dtype)
    part = jax.lax.dot(xe, scaled(lo), preferred_element_type=jnp.float32)
    part += jax.lax.dot(xo, scaled(hi), preferred_element_type=jnp.float32)

    @pl.when(kb == 0)
    def _init():
        o_ref[...] = part

    @pl.when(kb != 0)
    def _acc():
        o_ref[...] += part


def _pick_blocks(k, n, group_size, block_k, block_n):
    """Largest tile sizes that divide the problem AND keep every k-tile
    group-aligned (tile a multiple of the group, so the kernel's per-tile
    scale reshape is exact). Returns None if no legal tiling exists."""
    g = group_size
    if k % g:
        return None
    bk = (min(block_k, k) // g) * g  # round down to a group multiple...
    if bk == 0:
        bk = g  # ...unless the group itself is bigger: one group per tile
    while bk > 0 and k % bk:
        bk -= g
    if bk <= 0:
        return None
    bn = min(block_n, n)
    while bn >= 128 and n % bn:
        bn //= 2
    if bn < 128 or n % bn:
        return None
    return bk, bn


def quantized_matmul(x, q, scale, *, bits, block_k=512, block_n=512,
                     interpret=False):
    """``x [m, k] @ dequant(q, scale) [k, n] -> [m, n]`` in ``x.dtype``.

    ``q``/``scale`` follow ``ops/quantizer.py quantize_per_channel`` (+
    ``pack_int4`` for bits=4: q is uint8 [k/2, n]). Returns None when the
    shapes don't admit a legal tiling — the caller falls back to the XLA
    dequant path.
    """
    m, k = x.shape
    n = q.shape[-1]
    scale = scale.reshape(scale.shape[-3], n)        # [groups, n]
    groups = scale.shape[0]
    if k % groups:
        return None
    group_size = k // groups
    if bits == 4 and group_size % 2:
        return None
    picked = _pick_blocks(k, n, group_size, block_k, block_n)
    if picked is None:
        return None
    bk, bn = picked
    n_kb, n_nb = k // bk, n // bn
    ng_tile = bk // group_size

    # pad the token dim to the fp32 sublane count so tiny decode batches
    # (m = 1..7) still form a legal tile
    m_pad = max(8, ((m + 7) // 8) * 8)
    if m_pad != m:
        x = jnp.pad(x, ((0, m_pad - m), (0, 0)))

    # dtype-faithful dot: bf16 activations keep the MXU-native bf16 dot;
    # fp32 serving must NOT be silently truncated to bf16 (the XLA fallback
    # computes in fp32, and the two paths must agree beyond tileability)
    dot_dtype = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32

    grid = (n_nb, n_kb)  # k innermost: accumulator tile stays resident
    out_shape = jax.ShapeDtypeStruct((m_pad, n), jnp.float32)
    out_spec = pl.BlockSpec((m_pad, bn), lambda j, kb: (0, j),
                            memory_space=pltpu.VMEM)
    s_spec = pl.BlockSpec((ng_tile, bn), lambda j, kb: (kb, j),
                          memory_space=pltpu.VMEM)
    params = dict(
        grid=grid,
        out_specs=out_spec,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )
    if bits == 8:
        y = pl.pallas_call(
            functools.partial(_int8_kernel, n_groups=ng_tile,
                              dot_dtype=dot_dtype),
            in_specs=[
                pl.BlockSpec((m_pad, bk), lambda j, kb: (0, kb),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bk, bn), lambda j, kb: (kb, j),
                             memory_space=pltpu.VMEM),
                s_spec,
            ],
            **params,
        )(x, q, scale)
    elif bits == 4:
        xe, xo = x[:, 0::2], x[:, 1::2]              # [m_pad, k/2]
        bk2 = bk // 2
        y = pl.pallas_call(
            functools.partial(_int4_kernel, n_groups=ng_tile,
                              dot_dtype=dot_dtype),
            in_specs=[
                pl.BlockSpec((m_pad, bk2), lambda j, kb: (0, kb),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((m_pad, bk2), lambda j, kb: (0, kb),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bk2, bn), lambda j, kb: (kb, j),
                             memory_space=pltpu.VMEM),
                s_spec,
            ],
            **params,
        )(xe, xo, q, scale)
    else:
        return None
    return y[:m].astype(x.dtype)
