"""Learning-rate schedules.

TPU-native equivalent of the reference's ``runtime/lr_schedules.py``:
``LRRangeTest``, ``OneCycle``, ``WarmupLR``, ``WarmupDecayLR`` (reference :18-22).
Each schedule is a pure jittable function ``step -> lr`` (a jnp scalar), so it can be
traced into the train step; the object wrapper keeps the reference's
``step()``/``get_last_lr()`` API for user loops.
"""

import math

import jax.numpy as jnp

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class LRSchedule:
    """Stateful wrapper with the torch-scheduler-shaped API the reference exposes."""

    def __init__(self):
        self.last_step = 0

    def lr_at(self, step):
        raise NotImplementedError

    def step(self, increment=1):
        self.last_step += increment
        return self.get_last_lr()

    def get_last_lr(self):
        return [float(self.lr_at(jnp.asarray(self.last_step, jnp.float32)))]

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]

    def set_lr(self, lr):
        """Override the schedule's peak/base lr (engine.set_lr plumbing);
        subclasses whose shape has no single base lr override or refuse."""
        for attr in ("warmup_max_lr", "max_lr", "min_lr"):
            if hasattr(self, attr):
                setattr(self, attr, lr)
                return
        raise ValueError(f"{type(self).__name__} has no overridable base lr")


class WarmupLR(LRSchedule):
    """Linear/log warmup then constant (reference ``lr_schedules.py`` WarmupLR)."""

    def __init__(self, warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE):
        super().__init__()
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(warmup_num_steps, 2)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_factor(self, step):
        if self.warmup_type == WARMUP_LOG_RATE:
            return self.inverse_log_warm_up * jnp.log(jnp.maximum(step, 1.0))
        return step / self.warmup_num_steps

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        factor = jnp.clip(self._warmup_factor(step), 0.0, 1.0)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * factor


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero over total_num_steps (reference WarmupDecayLR)."""

    def __init__(self, total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE):
        super().__init__(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
        self.total_num_steps = total_num_steps

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warmup_lr = super().lr_at(step)
        decay = jnp.clip(
            (self.total_num_steps - step) / max(self.total_num_steps - self.warmup_num_steps, 1),
            0.0,
            1.0,
        )
        return jnp.where(step < self.warmup_num_steps, warmup_lr, self.warmup_max_lr * decay)


class OneCycle(LRSchedule):
    """Triangular cycle then decay (reference ``lr_schedules.py`` OneCycle)."""

    def __init__(self, cycle_min_lr, cycle_max_lr, cycle_first_step_size=2000,
                 cycle_second_step_size=None, decay_step_size=0,
                 decay_lr_rate=0.0, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, cycle_momentum=False,
                 cycle_min_mom=0.8, cycle_max_mom=0.9, decay_mom_rate=0.0):
        super().__init__()
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.first_size = cycle_first_step_size
        self.second_size = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.decay_lr_rate = decay_lr_rate
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        total_cycle = self.first_size + self.second_size
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= self.first_size,
            self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * up,
            self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * down,
        )
        if self.decay_step_size > 0:
            decay_steps = jnp.maximum(step - total_cycle, 0.0) / self.decay_step_size
            decay_lr = self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate)
            return jnp.where(step > total_cycle, decay_lr, in_cycle_lr)
        return in_cycle_lr

    def mom_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        return jnp.where(
            step <= self.first_size,
            self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * up,
            self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * down,
        )


class LRRangeTest(LRSchedule):
    """LR range test sweep (reference ``lr_schedules.py`` LRRangeTest)."""

    def __init__(self, lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0, lr_range_test_staircase=False):
        super().__init__()
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(step / self.step_size) if self.staircase else step / self.step_size
        return self.min_lr * (1.0 + interval * self.step_rate)


SCHEDULES = {
    "warmuplr": WarmupLR,
    "warmupdecaylr": WarmupDecayLR,
    "onecycle": OneCycle,
    "lrrangetest": LRRangeTest,
}


def get_lr_schedule(name, params=None):
    """Resolve by config name (reference ``engine.py:856`` _configure_lr_scheduler)."""
    key = name.lower().replace("_", "")
    if key not in SCHEDULES:
        raise ValueError(f"Unknown LR schedule '{name}'. Available: {sorted(SCHEDULES)}")
    return SCHEDULES[key](**(params or {}))
