"""fp16 loss scaling and overflow detection.

TPU-native equivalent of the reference's ``runtime/fp16/loss_scaler.py``
(``LossScaler``/``DynamicLossScaler``) and ``CheckOverflow`` (``runtime/utils.py:176``).

The scaler state is a small pytree of jnp scalars that lives inside the engine's
train state, so the scale update (check for non-finite grads -> halve scale / after a
clean window -> double scale) is traced into the jitted train step with ``lax.cond``
semantics via ``jnp.where`` — no host round-trip per step. The cross-replica overflow
propagation the reference does with an allreduce (``CheckOverflow.check``) falls out
for free: grads are already globally reduced when we inspect them.
"""

import jax
import jax.numpy as jnp


def make_scaler_state(static_scale=0.0, initial_scale_power=16, min_scale=1.0):
    """Initial scaler state. static_scale > 0 disables dynamic scaling
    (reference: ``fp16.loss_scale`` config; 0 means dynamic)."""
    if static_scale and static_scale > 0:
        scale = float(static_scale)
        dynamic = False
    else:
        scale = float(2.0 ** initial_scale_power)
        dynamic = True
    return {
        "scale": jnp.asarray(scale, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
        # static metadata rides outside the traced state
        "_dynamic": dynamic,
        "_min_scale": float(min_scale),
    }


def traced_state(state):
    return {"scale": state["scale"], "good_steps": state["good_steps"]}


def check_overflow(grads):
    """True iff any grad element is non-finite (reference ``CheckOverflow``,
    ``runtime/utils.py:176``; has_overflow_serial + allreduce)."""
    leaves = jax.tree_util.tree_leaves(grads)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    overflow = flags[0]
    for f in flags[1:]:
        overflow = jnp.logical_or(overflow, f)
    return overflow


def count_nonfinite(x):
    """Number of non-finite elements in one array, as an f32 scalar — the
    counting form of ``check_overflow`` (the health side output wants *how
    many and where*, not just a flag). Pure; safe inside jit."""
    return jnp.sum(jnp.logical_not(jnp.isfinite(x))).astype(jnp.float32)


def update_scale(scale, good_steps, overflow, loss_scale_window=1000, hysteresis=2,
                 min_scale=1.0, max_scale=2.0 ** 32):
    """Dynamic scale update (reference ``DynamicLossScaler.update_scale``):
    on overflow halve (bounded below), else after ``loss_scale_window`` clean steps
    double (bounded above). Pure; safe inside jit."""
    del hysteresis  # single-halve policy; reference hysteresis counts repeated overflows
    new_scale = jnp.where(
        overflow,
        jnp.maximum(scale * 0.5, min_scale),
        jnp.where(good_steps + 1 >= loss_scale_window, jnp.minimum(scale * 2.0, max_scale), scale),
    )
    new_good = jnp.where(
        overflow, 0, jnp.where(good_steps + 1 >= loss_scale_window, 0, good_steps + 1)
    )
    return new_scale, new_good


def scale_loss(loss, scale):
    return loss * scale.astype(loss.dtype)


def unscale_grads(grads, scale):
    inv = (1.0 / scale).astype(jnp.float32)
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * inv), grads)


def global_grad_norm(grads, eps=1e-6):
    """L2 norm over the whole grad pytree (reference ``get_global_norm`` /
    ``clip_grad_norm_`` in ``runtime/utils.py``). Under pjit the grads are global
    arrays, so no explicit cross-rank reduction is needed."""
    leaves = jax.tree_util.tree_leaves(grads)
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(sq + eps)


def clip_grads_by_global_norm(grads, max_norm, norm=None):
    """Reference ``clip_grad_norm_``: scale all grads by max_norm/global_norm if over."""
    if norm is None:
        norm = global_grad_norm(grads)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * factor.astype(g.dtype), grads), norm
