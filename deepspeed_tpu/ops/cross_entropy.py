"""Fused (vocab-chunked) softmax cross-entropy.

The reference computes the LM loss as a full [batch*seq, vocab] logit matrix
followed by softmax-cross-entropy (torch does the same); at vocab ~50k and fp32
that matrix is the single largest activation in the model — 3.3 GB for a
16x1024 batch — and it is materialized twice (fwd logits + bwd dlogits).

TPU-native replacement: the head matmul and the softmax-CE are one fused op,
chunked over the vocabulary with an online logsumexp — the [tokens, vocab]
matrix never exists. The backward recomputes each chunk's logits (one extra
tokens x d x vocab matmul, ~flops of the head itself) and streams
``dlogits_chunk @ E_chunk`` / ``dlogits_chunk^T @ x`` — O(tokens x d) memory.

This is the same trade the reference's fused training kernels make
(``csrc/transformer/softmax_kernels.cu``: recompute-in-bwd instead of
materialize) applied to the LM head, where it matters most on TPU.

API: embedding in vocab-major layout [V, d] (the tied-``wte`` convention).
"""

import functools

import jax
import jax.numpy as jnp


def _chunking(vocab, n_chunks):
    """(n_chunks, chunk, padded_vocab): uniform chunks via padding — a divisor
    search would silently fall back to ONE chunk for prime-ish vocabs (GPT-2's
    50257!) and materialize the full logit matrix, voiding the op entirely."""
    nc = max(1, min(n_chunks, vocab))
    chunk = -(-vocab // nc)  # ceil
    return nc, chunk, nc * chunk


def _pad_emb(emb, padded_vocab):
    vocab = emb.shape[0]
    if padded_vocab == vocab:
        return emb
    return jnp.pad(emb, ((0, padded_vocab - vocab), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_cross_entropy(x, emb, labels, bias=None, ignore_index=-100,
                        n_chunks=8, impl="xla", interpret=False):
    """Token-mean CE of ``softmax(x @ emb^T + bias)`` against ``labels``.

    x: [tokens, d] (compute dtype); emb: [V, d]; ``bias``: optional [V] logit
    bias (GPT-J-style biased LM head); labels: [tokens] int (``ignore_index``
    entries masked out). Returns a scalar fp32 loss.

    ``impl="pallas"`` streams the forward through the Pallas kernel
    (``ops/pallas/cross_entropy.py`` — chunk logits never touch HBM); the
    backward is the chunked XLA path either way (its cost is two MXU GEMMs
    XLA already runs at peak).
    """
    loss, _ = _ce_fwd_impl(x, emb, labels, bias, ignore_index, n_chunks,
                           impl, interpret)
    return loss


def _pad_bias(bias, padded_vocab):
    if bias is None or padded_vocab == bias.shape[0]:
        return bias
    return jnp.pad(bias, (0, padded_vocab - bias.shape[0]))


def _ce_fwd_impl(x, emb, labels, bias, ignore_index, n_chunks, impl="xla",
                 interpret=False):
    if impl not in ("xla", "pallas"):
        # checked here (not in the custom_vjp primal, which grad bypasses)
        # so a typo'd config can never silently bench the wrong kernel
        raise ValueError(f"fused_cross_entropy impl must be 'xla' or "
                         f"'pallas', got {impl!r}")
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)
    if impl == "pallas":
        from .pallas.cross_entropy import pallas_ce_forward

        lse, lab_logit = pallas_ce_forward(x, emb, safe_labels, bias,
                                           interpret=interpret)
        n_valid = jnp.maximum(jnp.sum(valid), 1)
        loss = jnp.sum((lse - lab_logit) * valid) / n_valid
        return loss, (lse, n_valid)
    tokens, d = x.shape
    vocab = emb.shape[0]
    nc, chunk, padded = _chunking(vocab, n_chunks)
    emb_c = _pad_emb(emb, padded).reshape(nc, chunk, d)
    bias_c = None if bias is None \
        else _pad_bias(bias, padded).reshape(nc, chunk)
    starts = jnp.arange(nc, dtype=jnp.int32) * chunk

    def body(carry, inp):
        m, s, lab_logit = carry
        e_c, b_c, c0 = inp
        logits = jax.lax.dot_general(
            x, e_c.astype(x.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [tokens, chunk]
        if b_c is not None:
            logits = logits + b_c.astype(jnp.float32)[None, :]
        if padded != vocab:
            # padded (fake-vocab) columns must not contribute to the logsumexp
            col = c0 + jnp.arange(chunk, dtype=jnp.int32)[None, :]
            logits = jnp.where(col < vocab, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        in_chunk = (safe_labels >= c0) & (safe_labels < c0 + chunk)
        idx = jnp.clip(safe_labels - c0, 0, chunk - 1)
        ll = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        lab_logit = jnp.where(in_chunk, ll, lab_logit)
        return (m_new, s, lab_logit), None

    m0 = jnp.full((tokens,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((tokens,), jnp.float32)
    ll0 = jnp.zeros((tokens,), jnp.float32)
    (m, s, lab_logit), _ = jax.lax.scan(body, (m0, s0, ll0),
                                        (emb_c, bias_c, starts))

    lse = m + jnp.log(s)
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum((lse - lab_logit) * valid) / n_valid
    return loss, (lse, n_valid)


def _ce_vjp_fwd(x, emb, labels, bias, ignore_index, n_chunks, impl,
                interpret):
    loss, (lse, n_valid) = _ce_fwd_impl(x, emb, labels, bias, ignore_index,
                                        n_chunks, impl, interpret)
    return loss, (x, emb, labels, bias, lse, n_valid)


def _ce_vjp_bwd(ignore_index, n_chunks, impl, interpret, residuals, g):
    x, emb, labels, bias, lse, n_valid = residuals
    tokens, d = x.shape
    vocab = emb.shape[0]
    nc, chunk, padded = _chunking(vocab, n_chunks)
    emb_c = _pad_emb(emb, padded).reshape(nc, chunk, d)
    bias_c = None if bias is None \
        else _pad_bias(bias, padded).reshape(nc, chunk)
    starts = jnp.arange(nc, dtype=jnp.int32) * chunk

    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)
    coef = (g / n_valid.astype(jnp.float32)) * valid.astype(jnp.float32)  # [tokens]

    def body(dx_acc, inp):
        e_c, b_c, c0 = inp
        logits = jax.lax.dot_general(
            x, e_c.astype(x.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [tokens, chunk]
        if b_c is not None:
            logits = logits + b_c.astype(jnp.float32)[None, :]
        p = jnp.exp(logits - lse[:, None])
        if padded != vocab:
            col = c0 + jnp.arange(chunk, dtype=jnp.int32)[None, :]
            p = jnp.where(col < vocab, p, 0.0)
        in_chunk = (safe_labels >= c0) & (safe_labels < c0 + chunk)
        idx = jnp.clip(safe_labels - c0, 0, chunk - 1)
        onehot = (jnp.arange(chunk, dtype=jnp.int32)[None, :] == idx[:, None]) \
            & in_chunk[:, None]
        dlogits = (p - onehot.astype(jnp.float32)) * coef[:, None]  # [tokens, chunk] f32
        dl16 = dlogits.astype(x.dtype)
        dx_acc = dx_acc + jax.lax.dot_general(
            dl16, e_c.astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [tokens, d]
        de_c = jax.lax.dot_general(
            dl16, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [chunk, d]
        db_c = jnp.sum(dlogits, axis=0)  # [chunk]
        return dx_acc, (de_c, db_c)

    dx0 = jnp.zeros((tokens, d), jnp.float32)
    dx, (de, db) = jax.lax.scan(body, dx0, (emb_c, bias_c, starts))
    de = de.reshape(padded, d)[:vocab]
    dbias = None if bias is None \
        else db.reshape(padded)[:vocab].astype(bias.dtype)
    return dx.astype(x.dtype), de.astype(emb.dtype), None, dbias


fused_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
