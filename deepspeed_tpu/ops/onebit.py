"""1-bit optimizers: OnebitAdam / OnebitLamb.

Reference: ``deepspeed/runtime/fp16/onebit/{adam,lamb}.py`` — Adam whose
gradient all-reduce is sign-compressed after a warmup phase, with error
compensation (the variance term is FROZEN at the end of warmup, which is what
makes sign-compression of the *momentum* communication sound — see the 1-bit
Adam paper's argument mirrored in ``onebit/adam.py:308``'s staged logic).

TPU-native shape: the compressed exchange is a ``shard_map`` collective
(``comm/compressed.py``), so these classes hold only the *local* update rule +
staging; the engine (or the test harness) wires the compressed allreduce of
momentum between ``local_momentum`` and ``apply``:

  warmup (step < freeze_step):  exact allreduce of grads, normal Adam, track v
  compressed (step >= freeze):  m = beta1 m + (1-beta1) g_local
                                m <- compressed_allreduce(m)   (1-bit + error)
                                p -= lr * m / (sqrt(v_frozen) + eps)
"""

import jax
import jax.numpy as jnp

from .optimizers import Adam, TPUOptimizer, _tree_zeros_like, _mask_like


class OnebitAdam(TPUOptimizer):
    """Staged Adam for compressed-momentum data parallelism."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.b1, self.b2 = betas
        self.eps = eps
        self.freeze_step = freeze_step
        self._adam = Adam(lr=lr, betas=betas, eps=eps,
                          weight_decay=weight_decay)

    def init(self, params):
        return {
            "exp_avg": _tree_zeros_like(params),
            "exp_avg_sq": _tree_zeros_like(params),
            "step": jnp.zeros((), jnp.int32),
            # step at which the variance was last tracked by an exact round —
            # the bias-correction horizon for the compressed stage
            "v_step": jnp.zeros((), jnp.int32),
        }

    def in_warmup(self, state):
        return state["step"] < self.freeze_step

    def wants_exact_step(self, step):
        """Host-side stage pick for the engine: True -> exact program."""
        return step < self.freeze_step

    def update(self, grads, state, params, lr=None, wd_mask=None):
        """Exact path == Adam (grads already mean-reduced); tracks v_step."""
        adam_state = {k: state[k] for k in ("exp_avg", "exp_avg_sq", "step")}
        new_params, s2 = self._adam.update(grads, adam_state, params, lr=lr,
                                           wd_mask=wd_mask)
        s2 = dict(s2)
        s2["v_step"] = s2["step"]
        return new_params, s2

    # -- compressed stage (engine calls these around the compressed collective)
    def local_momentum(self, grads, state):
        """Update m with the LOCAL gradient; returns the momentum tree to be
        compressed-allreduced (reference onebit/adam.py: momentum is what goes
        on the wire after freeze)."""
        m = jax.tree_util.tree_map(
            lambda mm, g: self.b1 * mm + (1 - self.b1) * g,
            state["exp_avg"], grads)
        return m

    def _trust_ratio(self, p, upd):
        """Per-leaf step-size modifier; identity for Adam, layerwise for LAMB."""
        return 1.0

    def apply_compressed(self, m_reduced, state, params, lr=None, wd_mask=None):
        """Apply the update using the reduced momentum and FROZEN variance.

        Bias correction must match the warmup phase: v was frozen at
        ``freeze_step``, so its correction uses the freeze-time horizon, not
        the current step — otherwise the denominator is ~(1-b2^freeze) too
        small and the compressed stage diverges."""
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        mask = _mask_like(wd_mask, params)
        c1 = 1.0 - self.b1 ** step.astype(jnp.float32)
        # v was last tracked at v_step (warmup end, or the latest 0/1-Adam
        # variance refresh) — correct with THAT horizon, not the current step
        c2 = 1.0 - self.b2 ** jnp.maximum(
            state["v_step"], 1).astype(jnp.float32)

        def leaf(p, m, v, decay):
            upd = (m / c1) / (jnp.sqrt(v / c2) + self.eps)
            if self.weight_decay:
                upd = upd + jnp.where(decay, self.weight_decay * p, 0.0)
            return p - lr * self._trust_ratio(p, upd) * upd

        new_params = jax.tree_util.tree_map(
            leaf, params, m_reduced, state["exp_avg_sq"], mask)
        new_state = {"exp_avg": m_reduced, "exp_avg_sq": state["exp_avg_sq"],
                     "step": step, "v_step": state["v_step"]}
        return new_params, new_state


class OnebitLamb(OnebitAdam):
    """LAMB layerwise trust ratio on top of the compressed-momentum update
    (reference ``onebit/lamb.py``)."""

    def _trust_ratio(self, p, upd):
        w_norm = jnp.linalg.norm(p.ravel())
        u_norm = jnp.linalg.norm(upd.ravel())
        return jnp.where((w_norm > 0) & (u_norm > 0),
                         w_norm / jnp.maximum(u_norm, 1e-30), 1.0)


class ZeroOneAdam(OnebitAdam):
    """0/1 Adam (reference ``onebit/zoadam.py``): compression starts almost
    immediately, and instead of freezing the variance forever, periodic EXACT
    synchronization rounds refresh the variance (and momentum) from true mean
    gradients; compressed momentum then resumes against the refreshed ``v``.

    Refreshes follow the reference's GROWING rule (``zoadam.py:267``):
    refresh when ``step % interval == 0``, interval starting at 1 and
    doubling after every ``var_update_scaler`` refreshes, so early training
    refreshes often and late training almost never — "the interval of
    updating variance will increase exponentially, so that it has negligible
    effect on the estimation" (``zoadam.py:265``). Past ``var_freeze_step``
    the variance freezes entirely.

    Deliberate deviation: the reference ALSO marks ``(step+1) % interval
    == 0`` steps for an exact round (``zoadam.py:273``) — a lookahead needed
    because its eager engine must arrange the NEXT step's uncompressed
    allreduce in advance. Here the engine picks the exact or compressed
    compiled program AT the step host-side, so the refresh step's gradient
    is exact by construction and no lookahead round exists; the exact-step
    SEQUENCE therefore differs from the reference's by that arrangement
    offset while the refresh cadence is the same. Setting
    ``var_update_interval`` > 0 opts into the legacy fixed interval.
    ``freeze_step`` keeps its warmup meaning and defaults low."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=2, var_update_interval=0,
                 var_freeze_step=100000, var_update_scaler=16):
        super().__init__(lr=lr, betas=betas, eps=eps,
                         weight_decay=weight_decay, freeze_step=freeze_step)
        self.var_update_interval = int(var_update_interval)
        self.var_freeze_step = int(var_freeze_step)
        self.var_update_scaler = max(1, int(var_update_scaler))
        # growing-schedule cursor (reference state['var_interval'] /
        # ['var_counter'], advanced monotonically; replayable from 0 so a
        # checkpoint resume at step N reconstructs the same schedule)
        self._sched = {"step": 0, "interval": 1, "counter": 0}

    def _refresh_at(self, step):
        """Replay the reference rule up to ``step``: refresh iff
        step % interval == 0, with interval doubling every
        ``var_update_scaler`` refreshes. The cursor advances monotonically
        (the engine queries increasing steps); a non-monotone query replays
        from 0 — O(step), rare, and fully deterministic."""
        if step < self._sched["step"]:
            self._sched = {"step": 0, "interval": 1, "counter": 0}
        s = self._sched
        refresh = False
        while s["step"] <= step:
            refresh = (s["step"] % s["interval"]) == 0
            if refresh:
                s["counter"] += 1
                if s["counter"] >= self.var_update_scaler:
                    s["counter"] = 0
                    s["interval"] *= 2
            s["step"] += 1
        return refresh

    def wants_exact_step(self, step):
        """True when ``step`` (0-based global step) should run the exact
        (uncompressed) program: warmup AND variance refreshes."""
        if step < self.freeze_step:
            return True
        if self.var_update_interval > 0:      # legacy fixed interval
            return (step % self.var_update_interval) == 0
        if step >= self.var_freeze_step:      # variance frozen for good
            return False
        return self._refresh_at(step)
