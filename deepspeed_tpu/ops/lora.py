"""LoRA adapters as pytree transforms.

Reference context: the hybrid (RLHF) engine fuses LoRA weights into the base
matrices before generation and unfuses after (``runtime/hybrid_engine.py:
120-146`` _fuse_lora/_unfuse_lora) so the inference path runs at full-matrix
speed. Here adapters are a parallel pytree and fuse/unfuse are pure functions
— no module surgery, and exact unfuse is trivial because fuse is ``W + s·A@B``
in fp32 masters.
"""

import jax
import jax.numpy as jnp


def _is_target(path, targets):
    joined = "/".join(str(getattr(p, "key", p)) for p in path)
    return any(t in joined for t in targets)


def lora_init(rng, params, rank=8, targets=("attn/q", "attn/v"), stddev=0.02):
    """Build {path: {"a": [in, r], "b": [r, out]}} for 2D+ kernels whose path
    matches ``targets``. b starts at zero so the adapter is a no-op initially
    (the standard LoRA init)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    adapters = {}
    key = rng
    for path, leaf in flat:
        if leaf.ndim < 2 or not _is_target(path, targets):
            continue
        joined = "/".join(str(getattr(p, "key", p)) for p in path)
        key, k1 = jax.random.split(key)
        in_dim, out_dim = leaf.shape[-2], leaf.shape[-1]
        lead = leaf.shape[:-2]  # stacked-layer dims ride along
        adapters[joined] = {
            "a": jax.random.normal(k1, lead + (in_dim, rank), jnp.float32) * stddev,
            "b": jnp.zeros(lead + (rank, out_dim), jnp.float32),
        }
    return adapters


def lora_delta(adapter, scale):
    return scale * adapter["a"] @ adapter["b"]


def fuse_lora(params, adapters, scale=1.0):
    """W <- W + s·A@B for every adapted kernel (pure; returns a new tree)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        joined = "/".join(str(getattr(p, "key", p)) for p in path)
        if joined in adapters:
            leaf = leaf + lora_delta(adapters[joined], scale).astype(leaf.dtype)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def unfuse_lora(params, adapters, scale=1.0):
    return fuse_lora(params, adapters, -scale)
