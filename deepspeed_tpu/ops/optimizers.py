"""Fused optimizers.

TPU-native equivalent of the reference's optimizer kernels:
- ``FusedAdam`` (``csrc/adam/multi_tensor_adam.cu`` via ``op_builder/fused_adam.py:11``)
- ``FusedLamb`` (``csrc/lamb/fused_lamb_cuda.cu``)
- ``CPUAdam``/``CPUAdagrad`` AVX kernels (``csrc/adam/cpu_adam.cpp``)
- ``OnebitAdam``-family error-compensated optimizers (``runtime/fp16/onebit/``)

On TPU there is nothing to hand-fuse: the whole tree-map update is one jitted XLA
program — the multi-tensor-apply machinery the CUDA kernels exist for is the
compiler's job. Optimizer state is a pytree shaped like the params, so ZeRO sharding
specs (state sharded over the data axis) apply transparently.

API: functional, jit-compatible.
    opt = get_optimizer("adamw", lr=1e-3, weight_decay=0.01)
    state = opt.init(params)
    new_params, new_state = opt.update(grads, state, params, lr=step_lr)

``lr`` at update time overrides the constructor value (the LR scheduler feeds it);
``wd_mask`` (pytree of bool, True = decay) supports the usual no-decay-on-
bias/LayerNorm grouping the reference expresses via param groups.
"""

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def _tree_zeros_like(params, dtype=None):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params
    )


def _mask_like(wd_mask, params, default=True):
    if wd_mask is None:
        return jax.tree_util.tree_map(lambda _: default, params)
    return wd_mask


class TPUOptimizer:
    """Base class: stateless transform with pytree state."""

    name = "base"

    def __init__(self, lr=1e-3, weight_decay=0.0):
        self.lr = lr
        self.weight_decay = weight_decay

    def init(self, params):
        raise NotImplementedError

    def update(self, grads, state, params, lr=None, wd_mask=None):
        raise NotImplementedError

    def hyperparams(self):
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}


class Adam(TPUOptimizer):
    """Adam/AdamW (reference ``FusedAdam``; ``adam_w_mode`` flag as in
    ``deepspeed/ops/adam/fused_adam.py``)."""

    name = "adam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adam_w_mode=True, bias_correction=True):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.betas = tuple(betas)
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction

    def init(self, params):
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "exp_avg": _tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": _tree_zeros_like(params, jnp.float32),
        }

    def update(self, grads, state, params, lr=None, wd_mask=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        wd_mask = _mask_like(wd_mask, params)

        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v, decay):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode and self.weight_decay:
                # classic Adam: L2 folded into the gradient
                g32 = jnp.where(decay, g32 + self.weight_decay * p32, g32)
            m_new = b1 * m + (1.0 - b1) * g32
            v_new = b2 * v + (1.0 - b2) * g32 * g32
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                update = jnp.where(decay, update + self.weight_decay * p32, update)
            p_new = p32 - lr * update
            return p_new.astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(leaf, params, grads, state["exp_avg"],
                                     state["exp_avg_sq"], wd_mask)
        new_params = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": step, "exp_avg": new_m, "exp_avg_sq": new_v}


class Adagrad(TPUOptimizer):
    """Adagrad (reference ``CPUAdagradBuilder`` / ``csrc/adagrad/cpu_adagrad.cpp``)."""

    name = "adagrad"

    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.eps = eps

    def init(self, params):
        return {"step": jnp.zeros((), jnp.int32), "sum_sq": _tree_zeros_like(params, jnp.float32)}

    def update(self, grads, state, params, lr=None, wd_mask=None):
        lr = self.lr if lr is None else lr
        wd_mask = _mask_like(wd_mask, params)

        def leaf(p, g, s, decay):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g32 = jnp.where(decay, g32 + self.weight_decay * p32, g32)
            s_new = s + g32 * g32
            p_new = p32 - lr * g32 / (jnp.sqrt(s_new) + self.eps)
            return p_new.astype(p.dtype), s_new

        out = jax.tree_util.tree_map(leaf, params, grads, state["sum_sq"], wd_mask)
        new_params = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_s = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": state["step"] + 1, "sum_sq": new_s}


class Lamb(TPUOptimizer):
    """LAMB (reference ``FusedLambBuilder`` / ``csrc/lamb/fused_lamb_cuda.cu``):
    Adam step rescaled per-layer by trust ratio ||p|| / ||update||."""

    name = "lamb"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
                 min_coeff=0.01, max_coeff=0.3):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.betas = tuple(betas)
        self.eps = eps
        self.min_coeff = min_coeff
        self.max_coeff = max_coeff

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": _tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": _tree_zeros_like(params, jnp.float32),
        }

    def update(self, grads, state, params, lr=None, wd_mask=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        wd_mask = _mask_like(wd_mask, params)

        def leaf(p, g, m, v, decay):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g32
            v_new = b2 * v + (1.0 - b2) * g32 * g32
            update = m_new / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay:
                update = jnp.where(decay, update + self.weight_decay * p32, update)
            w_norm = jnp.linalg.norm(p32.ravel())
            u_norm = jnp.linalg.norm(update.ravel())
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0),
            )
            p_new = p32 - lr * trust * update
            return p_new.astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(leaf, params, grads, state["exp_avg"],
                                     state["exp_avg_sq"], wd_mask)
        new_params = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": step, "exp_avg": new_m, "exp_avg_sq": new_v}


class SGD(TPUOptimizer):
    name = "sgd"

    def __init__(self, lr=1e-2, momentum=0.0, weight_decay=0.0, nesterov=False):
        super().__init__(lr=lr, weight_decay=weight_decay)
        self.momentum = momentum
        self.nesterov = nesterov

    def init(self, params):
        if self.momentum:
            return {"step": jnp.zeros((), jnp.int32), "momentum": _tree_zeros_like(params, jnp.float32)}
        return {"step": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr=None, wd_mask=None):
        lr = self.lr if lr is None else lr
        wd_mask = _mask_like(wd_mask, params)

        if not self.momentum:
            def leaf(p, g, decay):
                g32 = g.astype(jnp.float32)
                p32 = p.astype(jnp.float32)
                if self.weight_decay:
                    g32 = jnp.where(decay, g32 + self.weight_decay * p32, g32)
                return (p32 - lr * g32).astype(p.dtype)

            new_params = jax.tree_util.tree_map(leaf, params, grads, wd_mask)
            return new_params, {"step": state["step"] + 1}

        def leaf(p, g, buf, decay):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g32 = jnp.where(decay, g32 + self.weight_decay * p32, g32)
            buf_new = self.momentum * buf + g32
            d = g32 + self.momentum * buf_new if self.nesterov else buf_new
            return (p32 - lr * d).astype(p.dtype), buf_new

        out = jax.tree_util.tree_map(leaf, params, grads, state["momentum"], wd_mask)
        new_params = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
        new_buf = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"step": state["step"] + 1, "momentum": new_buf}


# Registry, mirroring the reference's optimizer-name dispatch in
# ``runtime/engine.py:1207`` (_configure_basic_optimizer). As in the reference,
# "adam" defaults to adam_w_mode=True (FusedAdam's default); pass
# {"adam_w_mode": false} for classic L2 Adam.
OPTIMIZERS = {
    "adam": lambda params: Adam(**{"adam_w_mode": True, **params}),
    "adamw": lambda params: Adam(**{**params, "adam_w_mode": True}),
    "fusedadam": lambda params: Adam(**params),
    "lamb": lambda params: Lamb(**params),
    "fusedlamb": lambda params: Lamb(**params),
    "adagrad": lambda params: Adagrad(**params),
    "sgd": lambda params: SGD(**params),
}

_TORCH_ARG_ALIASES = {"betas": "betas", "eps": "eps", "lr": "lr",
                      "weight_decay": "weight_decay", "momentum": "momentum"}
_IGNORED_ARGS = {"torch_adam", "fused", "set_grad_none", "amsgrad", "freeze_step",
                 "cuda_aware", "comm_backend_name"}


def get_optimizer(name, params=None):
    """Resolve an optimizer by config name (reference ``engine.py:1207``)."""
    key = name.lower().replace("_", "")
    kwargs = dict(params or {})
    # 1-bit variants: the staged compressed-momentum optimizers (ops/onebit.py).
    # The engine runs their compression stage when the mesh allows (pure-dp,
    # ZeRO<=1); elsewhere they degrade to exact numerics (update() == Adam/Lamb),
    # matching the reference's compression-off behavior.
    if key in ("onebitadam", "zerooneadam", "onebitlamb"):
        from .onebit import OnebitAdam, OnebitLamb, ZeroOneAdam

        cls = {"onebitadam": OnebitAdam, "onebitlamb": OnebitLamb,
               "zerooneadam": ZeroOneAdam}[key]
        allowed = ("lr", "betas", "eps", "weight_decay", "freeze_step")
        if key == "zerooneadam":
            allowed += ("var_update_interval", "var_freeze_step",
                        "var_update_scaler")
        ob_kwargs = {k: v for k, v in kwargs.items() if k in allowed}
        return cls(**ob_kwargs)
    if key not in OPTIMIZERS:
        raise ValueError(f"Unknown optimizer '{name}'. Available: {sorted(OPTIMIZERS)}")
    for bad in list(kwargs):
        if bad in _IGNORED_ARGS:
            kwargs.pop(bad)
    return OPTIMIZERS[key](kwargs)
