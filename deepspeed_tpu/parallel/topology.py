"""Named-axis process topology and device-mesh construction.

TPU-native equivalent of the reference's ``runtime/pipe/topology.py``:
``ProcessTopology`` (reference :12) — named-axis cartesian rank mapping — and
``PipeDataParallelTopology``/``PipeModelDataParallelTopology`` (reference :232/:244).
On TPU the topology *is* a ``jax.sharding.Mesh``; this module keeps the reference's
rank-math API (``get_rank``, ``get_coord``, ``get_axis_comm_lists``, filtering) because
launchers, checkpoint naming, and pipeline schedules all consume it, and builds the
Mesh from it.

Axis order convention: slower-varying axes first (the reference puts ``pipe`` outermost
for the same reason); for multi-slice TPU deployments the outermost axis should be the
one riding DCN (usually ``data``/``pipe``), inner axes ride ICI.
"""

import itertools
from collections import namedtuple

import numpy as np

from ..config.base import ConfigError

# Canonical mesh axis names for the whole framework. Everything (ZeRO sharding specs,
# TP rules, MoE all_to_all, ring attention, pipeline ppermute) refers to these names.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"

# Mesh layout order (outermost first). pipe/data outermost so that multi-slice DCN
# traffic is the low-frequency pipeline/data-parallel traffic.
CANONICAL_AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)


class ProcessCoord(dict):
    """Mapping axis-name -> coordinate, attribute-accessible like the reference's
    namedtuple coords (``topology.py:12``)."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name)


class ProcessTopology:
    """Cartesian product topology over named axes (reference ``topology.py:12``)."""

    def __init__(self, axes, dims):
        if len(axes) != len(dims):
            raise ConfigError(f"axes {axes} and dims {dims} length mismatch")
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        for name, d in zip(self.axes, self.dims):
            if d < 1:
                raise ConfigError(f"axis {name} has invalid size {d}")
        self._coord_cls = namedtuple("ProcessCoordT", self.axes)
        self.mapping = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self.dims])):
            self.mapping[self._coord_cls(*coord)] = rank

    def world_size(self):
        return int(np.prod(self.dims)) if self.dims else 1

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_rank(self, **coord_kwargs):
        """Rank of the process at the given full coordinate (reference :49)."""
        if sorted(coord_kwargs) != sorted(self.axes):
            raise ConfigError(f"get_rank requires all axes {self.axes}, got {sorted(coord_kwargs)}")
        return self.mapping[self._coord_cls(**coord_kwargs)]

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ConfigError(f"rank {rank} not in topology")

    def get_rank_repr(self, rank, omit_axes=(PIPE_AXIS, DATA_AXIS), inner_sep="_", outer_sep="-"):
        """String like 'model_00' used in checkpoint filenames (reference :81)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        coord = self.get_coord(rank)
        for ax in axes:
            names.append(f"{ax}{inner_sep}{getattr(coord, ax):02d}")
        return outer_sep.join(names)

    def get_axis_list(self, axis, idx):
        """All ranks whose coordinate along ``axis`` equals ``idx`` (reference :106)."""
        ax_idx = self.axes.index(axis)
        return sorted(rank for coord, rank in self.mapping.items() if coord[ax_idx] == idx)

    def get_axis_comm_lists(self, axis):
        """Communicator rank lists along ``axis``: for every combination of the other
        axes, the list of ranks that vary only in ``axis`` (reference :127). This is
        exactly what a process group / mesh-axis collective spans."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for combo in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            other = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i, **other}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks matching the partial coordinate (reference :153)."""
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return sorted(rank for coord, rank in self.mapping.items() if matches(coord))

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


def resolve_mesh_dims(mesh_config, n_devices):
    """Resolve a MeshConfig (-1 = infer on data axis) against the device count.

    Returns an ordered dict axis-name -> size following CANONICAL_AXIS_ORDER.
    """
    sizes = {
        PIPE_AXIS: mesh_config.pipe,
        DATA_AXIS: mesh_config.data,
        EXPERT_AXIS: mesh_config.expert,
        SEQ_AXIS: mesh_config.seq,
        MODEL_AXIS: mesh_config.model,
    }
    for name, v in sizes.items():
        if v == 0 or v < -1:
            raise ConfigError(f"Mesh axis '{name}' has invalid size {v} (use -1 to infer)")
    n_infer = sum(1 for v in sizes.values() if v == -1)
    if n_infer > 1:
        raise ConfigError("Only one mesh axis may be -1 (inferred)")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if n_infer == 1:
        if n_devices % fixed:
            raise ConfigError(
                f"Cannot infer mesh axis: {n_devices} devices not divisible by {fixed}"
            )
        for k, v in sizes.items():
            if v == -1:
                sizes[k] = n_devices // fixed
    else:
        if fixed != n_devices:
            raise ConfigError(
                f"Mesh {sizes} has {fixed} slots but there are {n_devices} devices"
            )
    return {ax: sizes[ax] for ax in CANONICAL_AXIS_ORDER}


def build_mesh(mesh_config=None, devices=None):
    """Build the framework-wide ``jax.sharding.Mesh``.

    The reference builds process groups per axis from ``ProcessTopology``
    (``topology.py:251`` ``PipelineParallelGrid``); here one Mesh with named axes
    replaces all of them — XLA collectives take the axis name.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if mesh_config is None:
        from ..config.config import MeshConfig

        mesh_config = MeshConfig()
    dims = resolve_mesh_dims(mesh_config, len(devices))
    axis_names = tuple(dims.keys())
    shape = tuple(dims.values())
    # mesh_utils gives ICI-aware device orderings on real TPU slices; fall back to a
    # plain reshape for CPU/virtual devices.
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, axis_names)


class PipelineParallelGrid:
    """Rank bookkeeping for pipeline runs (reference ``topology.py:251``).

    Carries the topology plus convenience accessors (stage id, dp id, adjacent
    stages). Collectives themselves go through mesh axis names, not rank lists.
    """

    def __init__(self, topology):
        self._topo = topology
        self.pipe_parallel_size = topology.get_dim(PIPE_AXIS) or 1
        self.data_parallel_size = topology.get_dim(DATA_AXIS) or 1
        self.model_parallel_size = topology.get_dim(MODEL_AXIS) or 1

    @property
    def topology(self):
        return self._topo

    def stage_of_rank(self, rank):
        if PIPE_AXIS not in self._topo.axes:
            return 0
        return getattr(self._topo.get_coord(rank), PIPE_AXIS)

    def dp_group_of_rank(self, rank):
        if DATA_AXIS not in self._topo.axes:
            return [rank]
        coord = self._topo.get_coord(rank)
        other = {a: getattr(coord, a) for a in self._topo.axes if a != DATA_AXIS}
        return self._topo.filter_match(**other)

    def stage_to_global(self, stage_id, **kwargs):
        return self._topo.filter_match(**{PIPE_AXIS: stage_id, **kwargs})

    def is_first_stage(self, rank):
        return self.stage_of_rank(rank) == 0

    def is_last_stage(self, rank):
        return self.stage_of_rank(rank) == self.pipe_parallel_size - 1


def topology_from_mesh_dims(dims):
    """ProcessTopology over the canonical axes with the given sizes dict."""
    axes = list(CANONICAL_AXIS_ORDER)
    return ProcessTopology(axes=axes, dims=[dims.get(a, 1) for a in axes])
