"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

First-class sequence/context parallelism — the capability the reference lacks in
0.9.1 (SURVEY §5: no Ulysses/ring/context-parallel; its long-sequence story is
block-sparse attention + activation partitioning, ``deepspeed/ops/sparse_attention``,
``activation_checkpointing/checkpointing.py:366``). Here long context is a mesh
axis: activations shard the sequence dim over ``seq``, and attention runs as a ring
(Liu et al., Ring Attention; see PAPERS.md):

- each device holds its local Q block and a rotating K/V block;
- ``S`` ring steps: compute one attention tile with flash-style online-softmax
  accumulators (m, l, o), then ``ppermute`` the K/V block to the next device —
  compute and ICI transfer overlap, peak memory is O(s_local^2 / S) per tile;
- causal masking uses global block offsets; the ring starts on the device's own
  diagonal block so row maxima are real before any fully-masked tile arrives;
- the whole loop is differentiable (scan + ppermute transpose), giving the
  backward ring for free.

Implemented with ``jax.shard_map(axis_names={'seq'})`` — manual over ``seq`` only,
so data/model/pipe sharding still compose via the SPMD partitioner.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.pallas.flash_attention import _fit_block as _fit_inner
from .topology import SEQ_AXIS

_NEG = -1e30


def _ring_attention_local(q, k, v, kv_mask, *, scale, causal, remat_steps,
                          inner_block=None):
    """Per-device body. q/k/v: [b, sl, h, dh] local blocks; kv_mask: [b, sl] bool
    for the local K/V block (True = attend) or None.

    ``inner_block``: chunk each ring tile's kv axis so the per-step score
    matrix is [b, h, sl, inner_block] instead of [b, h, sl, sl] — online
    softmax is associative, so the inner chunk scan carries the same
    (o, m, l) triple. At long per-device sequence this turns the ring's
    peak memory from O(sl^2) into O(sl * inner_block)."""
    S = jax.lax.axis_size(SEQ_AXIS)
    my_idx = jax.lax.axis_index(SEQ_AXIS)
    b, sl, h, dh = q.shape

    o = jnp.zeros((b, sl, h, dh), jnp.float32)
    m = jnp.full((b, h, sl), _NEG, jnp.float32)
    l = jnp.zeros((b, h, sl), jnp.float32)

    # rotate kv around the ring: at step r we hold the block of device
    # (my_idx - r) mod S; sending to the next device advances everyone's r.
    perm = [(i, (i + 1) % S) for i in range(S)]
    q_pos = my_idx * sl + jnp.arange(sl)

    inner = _fit_inner(inner_block, sl) if inner_block else sl
    n_inner = sl // inner

    def tile_update(o, m, l, k_sub, v_sub, kv_pos, mask_sub):
        """One online-softmax update against a kv chunk (any width)."""
        # bf16 dot inputs + fp32 accumulation (MXU native mode) — upcasting
        # q/k to fp32 first would run fp32xfp32 matmuls at a fraction of
        # bf16 throughput
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_sub,
                            preferred_element_type=jnp.float32) * scale
        allowed = jnp.ones((sl, kv_pos.shape[0]), bool)
        if causal:
            allowed = q_pos[:, None] >= kv_pos[None, :]
        if mask_sub is not None:
            allowed = allowed & mask_sub[:, None, None, :]
        scores = jnp.where(allowed, scores, _NEG)

        blk_max = jnp.max(scores, axis=-1)            # [b, h, q]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])        # [b, h, q, k]
        new_l = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_sub.dtype), v_sub,
                        preferred_element_type=jnp.float32)
        new_o = o * correction.transpose(0, 2, 1)[..., None] + pv
        return new_o, new_m, new_l

    def step(carry, r):
        o, m, l, k_blk, v_blk, mask_blk = carry
        kv_idx = (my_idx - r) % S
        kv_base = kv_idx * sl

        if n_inner == 1:
            o, m, l = tile_update(o, m, l, k_blk, v_blk,
                                  kv_base + jnp.arange(sl), mask_blk)
        else:
            def sub(c2, t):
                o2, m2, l2 = c2
                k_sub = jax.lax.dynamic_slice_in_dim(k_blk, t * inner, inner, 1)
                v_sub = jax.lax.dynamic_slice_in_dim(v_blk, t * inner, inner, 1)
                m_sub = (jax.lax.dynamic_slice_in_dim(mask_blk, t * inner,
                                                      inner, 1)
                         if mask_blk is not None else None)
                kv_pos = kv_base + t * inner + jnp.arange(inner)
                return tile_update(o2, m2, l2, k_sub, v_sub, kv_pos, m_sub), None

            (o, m, l), _ = jax.lax.scan(sub, (o, m, l),
                                        jnp.arange(n_inner))

        k_nxt = jax.lax.ppermute(k_blk, SEQ_AXIS, perm)
        v_nxt = jax.lax.ppermute(v_blk, SEQ_AXIS, perm)
        mask_nxt = (jax.lax.ppermute(mask_blk, SEQ_AXIS, perm)
                    if mask_blk is not None else None)
        return (o, m, l, k_nxt, v_nxt, mask_nxt), None

    if remat_steps:
        step = jax.checkpoint(step)
    (o, m, l, *_), _ = jax.lax.scan(step, (o, m, l, k, v, kv_mask), jnp.arange(S))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_manual(q, k, v, *, kv_mask=None, causal=True, scale=None,
                          remat_steps=True, inner_block=None):
    """Ring attention for callers ALREADY inside a manual region whose axis set
    includes ``seq`` (e.g. the pipeline's shard_map with
    ``axis_names={'pipe','seq'}`` — shard_maps don't nest, so the pipeline
    cannot call the wrapped ``ring_attention``). q/k/v are the LOCAL sequence
    blocks [b, s_local, h, dh]; global causal offsets come from
    ``axis_index('seq')`` exactly as in the wrapped version."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_attention_local(q, k, v, kv_mask, scale=scale, causal=causal,
                                 remat_steps=remat_steps,
                                 inner_block=inner_block)


def ring_attention(q, k, v, mesh, *, kv_mask=None, causal=True, scale=None,
                   remat_steps=True, inner_block=None):
    """Exact attention with the sequence dim sharded over the ``seq`` mesh axis.

    Args:
      q, k, v: [batch, seq, heads, head_dim] (seq GLOBAL; sharded over ``seq``
        by the surrounding program — in_specs reshard if needed).
      mesh: device mesh containing a ``seq`` axis.
      kv_mask: optional [batch, seq] bool, True = key position attendable
        (padding masks; rotates around the ring with K/V).
      causal: apply causal masking on global positions.
      remat_steps: recompute each ring tile in backward (O(s_local) memory).
      inner_block: chunk each ring tile's kv axis (see _ring_attention_local)
        — peak memory O(s_local * inner_block) instead of O(s_local^2).

    Returns [batch, seq, heads, head_dim], same dtype as q.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    S = mesh.shape[SEQ_AXIS]
    if q.shape[1] % S:
        raise ValueError(f"seq len {q.shape[1]} not divisible by seq axis {S}")

    fn = functools.partial(_ring_attention_local, scale=scale, causal=causal,
                           remat_steps=remat_steps, inner_block=inner_block)
    qkv_spec = P(None, SEQ_AXIS, None, None)
    mask_spec = P(None, SEQ_AXIS)
    if kv_mask is None:
        body = lambda q, k, v: fn(q, k, v, None)
        in_specs = (qkv_spec, qkv_spec, qkv_spec)
        args = (q, k, v)
    else:
        body = fn
        in_specs = (qkv_spec, qkv_spec, qkv_spec, mask_spec)
        args = (q, k, v, kv_mask)
    sm = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec,
        axis_names={SEQ_AXIS}, check_vma=False,
    )
    return sm(*args)
