"""Pipeline parallelism: a compiled GPipe microbatch loop over the ``pipe`` mesh axis.

TPU-native equivalent of the reference's pipeline engine
(``runtime/pipe/engine.py:40`` / ``schedule.py:189`` / ``p2p.py``): the reference
interprets an instruction list per step (1F1B ``TrainSchedule``) and moves
activations with ``dist.send/recv`` between stage processes. Here the whole schedule
is ONE differentiable XLA program:

- the layer stack (leading ``layers`` dim) is sharded over the ``pipe`` axis, so each
  stage holds ``n_layers / n_stages`` contiguous layers — the reference's
  ``PipelineModule._partition_layers(method='uniform')`` (``pipe/module.py:353``);
- a ``lax.scan`` runs ``M + S - 1`` ticks; each tick every stage applies its local
  layers to its in-flight microbatch, then ``ppermute`` rotates activations to the
  next stage — the Send/RecvActivation instructions (``pipe/engine.py:907,:999``)
  become one ICI collective-permute;
- reverse-mode AD through the scan+ppermute yields the backward pipeline (grads flow
  stage S-1 -> 0 via the transposed permute) — the reference's Send/RecvGrad
  instructions for free, with identical bubble structure to GPipe;
- shapes are static, so the activation-meta handshake (``pipe/engine.py:789
  _send_tensor_meta``) disappears by construction.

Implementation notes:
- ``jax.shard_map(axis_names={'pipe'})``: the program is *manual* over ``pipe`` only;
  ``data`` / ``model`` / ``seq`` stay under the SPMD partitioner, so ZeRO sharding
  and tensor parallelism compose with the pipeline without hand-written collectives.
- batched side inputs (padding masks, rope tables built from per-row positions)
  travel WITH their microbatch through the ppermute rotation, so every stage sees
  the side inputs matching its in-flight microbatch.
- microbatch accounting: with M microbatches and S stages the bubble fraction is
  (S-1)/(M+S-1); gradient accumulation happens inside the loop (sum over
  microbatches), mirroring how the reference folds grad-accum into the schedule.
- the last stage's outputs are made pipe-replicated with a masked ``psum`` so the
  LM head / loss can run outside the manual region.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .topology import PIPE_AXIS, DATA_AXIS, SEQ_AXIS


def pipeline_stack_apply(cfg, stacked_params, x, *, mesh, n_microbatches,
                         block_fn, side=None, seq_manual=False):
    """Run stacked transformer blocks pipelined over the ``pipe`` mesh axis.

    Args:
      cfg: model config (provides ``n_layers``).
      stacked_params: pytree of arrays with leading ``layers`` dim (sharded over
        ``pipe``).
      x: [batch, seq, d_model] activations (batch sharded over ``data``).
      mesh: the device mesh; must contain a ``pipe`` axis of size S > 1.
      n_microbatches: M; batch must be divisible by M.
      block_fn: ``block_fn(params_i, h, side_mb, layer_idx, mb_idx) -> (h, aux)`` —
        one transformer block (already remat-wrapped by the caller). ``side_mb`` is
        the per-microbatch slice of ``side``; ``mb_idx`` identifies the in-flight
        microbatch (for per-microbatch rng folding); ``aux`` is a scalar auxiliary
        loss (MoE load balancing), summed over layers and microbatches.
      side: optional pytree of per-row side inputs with leading dim == batch
        (padding mask, rope cos/sin). Unbatched side inputs should be closed over
        in ``block_fn`` instead.

    Returns: ``(y, aux)`` — [batch, seq, d_model] transformed activations and the
    summed auxiliary loss, both pipe-replicated.
    """
    S = mesh.shape[PIPE_AXIS]
    M = int(n_microbatches)
    if M < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {M}")
    b, s, d = x.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by pipeline microbatches {M}")
    n_layers = cfg.n_layers
    if n_layers % S:
        raise ValueError(f"n_layers {n_layers} not divisible by pipeline stages {S}")
    layers_per_stage = n_layers // S
    side = side if side is not None else {}

    # [b, ...] -> [M, mb, ...] for activations and every batched side input; keep the
    # microbatch rows sharded over data. When the manual region includes ``seq``
    # the sequence dim (index 2 after microbatching) must be seq-sharded HERE as
    # well: this constraint's transpose runs in the backward, and if its layout
    # disagrees with the shard_map boundary spec the SPMD partitioner falls back
    # to an involuntary full rematerialization (replicate-then-reshard) of the
    # cotangent every step.
    seq_size = mesh.shape.get(SEQ_AXIS, 1)

    def to_microbatches(a):
        a = a.reshape((M, b // M) + a.shape[1:])
        entries = [None, DATA_AXIS] + [None] * (a.ndim - 2)
        if (seq_manual and seq_size > 1 and a.ndim >= 3
                and a.shape[2] % seq_size == 0):
            entries[2] = SEQ_AXIS
        spec = P(*entries)
        return jax.lax.with_sharding_constraint(a, jax.sharding.NamedSharding(mesh, spec))

    # Cross the shard_map boundary in f32: for replicated (P()) inputs, reverse-mode
    # AD inserts a psum over ``pipe`` of the cotangent, and XLA's partial-manual
    # partitioner miscompiles bf16/f16 all-reduces ("Invalid binary instruction
    # opcode copy"). Activations are cast back to the compute dtype inside.
    compute_dtype = x.dtype
    boundary_f32 = compute_dtype in (jnp.bfloat16, jnp.float16)

    def to_boundary(a):
        return a.astype(jnp.float32) if boundary_f32 and a.dtype == compute_dtype else a

    xs = to_microbatches(to_boundary(x))
    side_ms = jax.tree_util.tree_map(
        lambda a: to_microbatches(to_boundary(a)), side)

    def local_layers(w, h, side_mb, stage, mb_idx):
        def body(carry, w_i):
            h, i, aux = carry
            h, aux_i = block_fn(w_i, h, side_mb, stage * layers_per_stage + i, mb_idx)
            return (h, i + 1, aux + aux_i), None

        (h, _, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.float32)), w
        )
        return h, aux

    perm = [(i, (i + 1) % S) for i in range(S)]

    def pipe_fn(w, xs, side_ms):
        stage = jax.lax.axis_index(PIPE_AXIS)
        T = M + S - 1
        state = {"h": jnp.zeros(xs.shape[1:], compute_dtype),
                 "side": jax.tree_util.tree_map(lambda a: jnp.zeros_like(a[0]), side_ms),
                 "mb": jnp.zeros((), jnp.int32)}
        outs = jnp.zeros(xs.shape, compute_dtype)
        aux0 = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            state, outs, aux_acc = carry
            # stage 0 injects microbatch t (LoadMicroBatch, pipe/engine.py:748)
            tm = jnp.clip(t, 0, M - 1)
            inj = {"h": jax.lax.dynamic_index_in_dim(xs, tm, 0,
                                                     keepdims=False).astype(compute_dtype),
                   "side": jax.tree_util.tree_map(
                       lambda a: jax.lax.dynamic_index_in_dim(a, tm, 0, keepdims=False),
                       side_ms),
                   "mb": tm}
            state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(stage == 0, new, old), inj, state)
            h, aux_i = local_layers(w, state["h"], state["side"], stage, state["mb"])
            # bubble ticks compute on garbage; only in-window ticks contribute aux
            valid = (t >= stage) & (t < stage + M)
            aux_acc = aux_acc + jnp.where(valid, aux_i, 0.0)
            # last stage collects microbatch t-(S-1)
            idx = t - (S - 1)
            sel = (stage == S - 1) & (idx >= 0)
            cidx = jnp.clip(idx, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, cidx, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(sel, h, cur), cidx, 0
            )
            # rotate the microbatch (activations + its side inputs + identity) to
            # the next stage (Send/RecvActivation as one collective-permute)
            nxt = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, PIPE_AXIS, perm),
                {"h": h, "side": state["side"], "mb": state["mb"]})
            return (nxt, outs, aux_acc), None

        (state, outs, aux_acc), _ = jax.lax.scan(tick, (state, outs, aux0),
                                                 jnp.arange(T))
        # make the last stage's outputs pipe-replicated for the head/loss; aux is
        # summed across stages (each stage contributed its own layers' aux).
        # psum in f32: XLA's partial-manual partitioner builds an invalid bf16
        # all-reduce combiner ("Invalid binary instruction opcode copy")
        out_dtype = outs.dtype
        outs = jax.lax.psum(
            jnp.where(stage == S - 1, outs, jnp.zeros((), outs.dtype))
            .astype(jnp.float32),
            PIPE_AXIS,
        ).astype(out_dtype)
        # mean over microbatches (each microbatch computes aux over its own
        # tokens, like the reference's per-micro-step accumulation; the mean keeps
        # the scale equal to a single full-batch aux term)
        aux = jax.lax.psum(aux_acc, PIPE_AXIS) / M
        return outs, aux

    param_specs = jax.tree_util.tree_map(lambda _: P(PIPE_AXIS), stacked_params)
    if seq_manual:
        # sequence parallelism composes by widening the manual region to
        # {pipe, seq}: activations/side inputs enter seq-sharded on their
        # sequence dim and the block's ring attention runs its seq-axis
        # ppermutes directly (shard_maps don't nest).
        xs_spec = P(None, None, SEQ_AXIS)
        side_specs = jax.tree_util.tree_map(
            lambda a: P(None, None, SEQ_AXIS) if a.ndim >= 3 else P(), side_ms)
        axis_names = {PIPE_AXIS, SEQ_AXIS}
    else:
        xs_spec = P()
        side_specs = jax.tree_util.tree_map(lambda _: P(), side_ms)
        axis_names = {PIPE_AXIS}
    sm = jax.shard_map(
        pipe_fn,
        mesh=mesh,
        in_specs=(param_specs, xs_spec, side_specs),
        out_specs=(xs_spec, P()),
        axis_names=axis_names,
        check_vma=False,
    )
    outs, aux = sm(stacked_params, xs, side_ms)
    return outs.reshape(b, s, d), aux
