from .topology import (
    ProcessTopology,
    PipelineParallelGrid,
    build_mesh,
    resolve_mesh_dims,
    topology_from_mesh_dims,
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    EXPERT_AXIS,
    CANONICAL_AXIS_ORDER,
)

__all__ = [
    "ProcessTopology",
    "PipelineParallelGrid",
    "build_mesh",
    "resolve_mesh_dims",
    "topology_from_mesh_dims",
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "EXPERT_AXIS",
    "CANONICAL_AXIS_ORDER",
]
