"""1F1B pipeline schedule as one compiled SPMD program.

TPU-native equivalent of the reference's ``TrainSchedule``
(``runtime/pipe/schedule.py:189``): the 1F1B interleave that bounds in-flight
activations to O(stages) instead of O(microbatches). The reference interprets an
instruction list per step, moving tensors with ``dist.send/recv``
(``pipe/engine.py:1273 _INSTRUCTION_MAP``); here the whole schedule — including
the backward passes — is a single ``lax.scan`` over global ticks inside one
``shard_map`` over the ``pipe`` mesh axis.

Why not AD through the GPipe scan (``parallel/pipeline.py``)? Reverse-mode AD
runs ALL forwards before ANY backward, so the saved microbatch activations grow
with M. 1F1B interleaves them, which AD cannot express — so this module computes
gradients *manually* with per-tick ``jax.vjp`` calls:

- schedule: stage ``s`` runs the forward of microbatch ``m`` at tick
  ``F(s,m) = s + 2m`` and its backward at tick ``B(s,m) = 2S-1-s + 2m``.
  Forward ticks have parity ``s mod 2``, backward ticks the opposite parity, so
  a stage never needs both in one tick; producers always run exactly one tick
  before consumers (``F(s,m)+1 = F(s+1,m)``, ``B(s+1,m)+1 = B(s,m)``), so a
  received activation/cotangent is consumed immediately — no queues.
- each tick does ``lax.cond(is_fwd)`` / ``lax.cond(is_bwd)``: XLA conditionals
  execute only the taken branch at runtime, so a tick costs one fwd OR one
  recompute+bwd, and the branches contain no collectives (the two ``ppermute``
  rotations — activations forward, cotangents backward — run unconditionally
  outside the conds; the reference's Send/Recv{Activation,Grad} instructions).
- the stage keeps a ring buffer of S saved *stage inputs* (its only residual);
  the backward tick recomputes the stage forward under ``jax.vjp`` — the same
  per-stage recompute the reference gets from activation checkpointing with
  ``checkpoint_interval = layers_per_stage``.
- the loss head (final norm + LM head + CE) runs inside the LAST stage's
  backward tick (``lax.cond(stage == S-1)``), seeding the cotangent chain; the
  first stage's input-cotangents are collected and returned so the embedding
  backward can run outside under plain SPMD AD.
- tied embeddings: the head's ``wte`` grad (last stage) is psum-masked out of
  the pipe region and ADDED to the embedding's ``wte`` grad — the reference's
  tied-weight allreduce (``pipe/module.py:406``) by construction.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .topology import PIPE_AXIS, DATA_AXIS


def _to_microbatches(a, M, mesh):
    a = a.reshape((M, a.shape[0] // M) + a.shape[1:])
    spec = P(*((None, DATA_AXIS) + (None,) * (a.ndim - 2)))
    return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))


def build_1f1b_train_step(model, mesh, n_microbatches, blocks_param_specs=None):
    """Returns ``train_step(params, batch, scale, rng) -> (loss, grads)`` — the
    1F1B replacement for the engine's ``fwd_bwd`` pass on pipe meshes.

    Tensor parallelism composes by widening the manual region to
    {pipe, model} and running the block in ``tp_manual`` mode (explicit
    row-parallel psums) — the auto partitioner cannot place model-axis
    collectives inside the schedule's stage-varying ``lax.cond`` branches
    (runtime deadlock), so the block writes them itself.
    ``blocks_param_specs``: the engine's PartitionSpec tree for
    ``params['blocks']`` (supplies the model-axis layout of each leaf).
    """
    cfg = model.config
    S = mesh.shape[PIPE_AXIS]
    TP = mesh.shape.get("model", 1)
    M = int(n_microbatches)
    if cfg.n_layers % S:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by stages {S}")
    L_local = cfg.n_layers // S
    # manual TP only when the caller supplies the model-axis layout; without
    # specs a TP-sized mesh keeps the block weights model-replicated (valid,
    # just unsharded — direct/test callers)
    tp_manual = TP > 1 and blocks_param_specs is not None
    if tp_manual:
        # Every matmul kernel must actually shard over 'model': a replicated
        # kernel (e.g. a TP-indivisible dim fell back in logical_to_physical)
        # would compute the FULL output per rank and the row-parallel psum
        # would then multiply it by TP — silent corruption. All-or-nothing.
        kernel_specs = [
            s for path, s in jax.tree_util.tree_flatten_with_path(
                blocks_param_specs, is_leaf=lambda x: isinstance(x, P))[0]
            if any(getattr(k, "key", None) == "kernel" for k in path)
        ]
        if not kernel_specs or not all("model" in tuple(s) for s in kernel_specs):
            from ..utils.logging import logger

            logger.warning(
                "1F1B x TP: not every block kernel shards over 'model' "
                "(indivisible dims?); keeping weights model-replicated")
            tp_manual = False

    from ..models import layers as Lyr
    from ..models.transformer import block_apply, _norm_apply, _remat_policy

    def pipe_block(p, h, side_mb, rng):
        m = side_mb.get("mask")
        r = ((side_mb["rope_cos"], side_mb["rope_sin"])
             if "rope_cos" in side_mb else side_mb.get("_rope_const"))
        return block_apply(cfg, p, h, mask=m, rope=r,
                           alibi=side_mb.get("_alibi_const"),
                           deterministic=side_mb.get("_det", True),
                           dropout_rng=rng, tp_manual=tp_manual)

    def head_loss(head_w, h, labels_mb):
        x = _norm_apply(cfg, head_w["ln_f"], h)
        return model.head_ce(head_w, x, labels_mb)

    def train_step(params, batch, scale, rng):
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1)
        B, s = input_ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        positions = batch.get("position_ids")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (B, s))
        attention_mask = batch.get("attention_mask")

        deterministic = rng is None
        compute_dtype = cfg.compute_dtype

        # ---- side inputs (masks / rope / alibi), same policy as the GPipe path:
        # batched ones ride per-microbatch, static ones are closed over.
        side = {}
        static_side = {"_det": deterministic}
        if attention_mask is not None:
            mask = Lyr.causal_mask(s, s) & attention_mask[:, None, None, :].astype(bool)
            side["mask"] = mask
        if cfg.position_embedding == "rope":
            cos, sin = Lyr.rotary_embedding(
                positions, cfg.rotary_dim or cfg.head_dim, cfg.rope_base)
            side["rope_cos"], side["rope_sin"] = cos, sin
        if cfg.position_embedding == "alibi":
            static_side["_alibi_const"] = Lyr.alibi_bias(cfg.n_heads, s, s)

        side_ms = jax.tree_util.tree_map(lambda a: _to_microbatches(a, M, mesh), side)

        # ---- embedding under vjp (plain SPMD; pipe sees only its output)
        embed_keys = ["wte"] + [k for k in ("wpe", "ln_emb") if k in params]
        embed_w = {k: params[k] for k in embed_keys}

        # NOTE: the microbatch reshape + sharding constraint live OUTSIDE the
        # vjp — constraining the gather output inside it makes XLA's SPMD
        # partitioner take the explicit-batch-dim gather path, which CHECK-fails
        # under tensor parallelism (spmd_partitioner_util.cc gather groups).
        def embed_all(ew):
            x = Lyr.embedding_apply(ew["wte"], input_ids, compute_dtype)
            if cfg.position_embedding == "learned":
                x = x + jnp.take(ew["wpe"]["weight"].astype(compute_dtype),
                                 positions, axis=0)
            if cfg.embed_layernorm:
                x = _norm_apply(cfg, ew["ln_emb"], x)
            # cross the shard_map boundary in f32 (see parallel/pipeline.py)
            return x.astype(jnp.float32)

        x_flat, embed_vjp = jax.vjp(embed_all, embed_w)
        xs = _to_microbatches(x_flat, M, mesh)

        head_keys = ["ln_f"] + (["wte"] if cfg.tie_embeddings else ["lm_head"])
        # Replicate the head weights across the non-pipe axes before entering the
        # manual region: TP-sharded head weights make the auto-axis partitioner
        # insert model-axis collectives inside the stage-varying lax.cond
        # branches, which the runtime cannot rendezvous (deadlock) — and the
        # vocab-sharded label gather CHECK-fails outright.
        head_w = {
            k: jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, P())), params[k])
            for k in head_keys
        }
        labels_ms = _to_microbatches(labels, M, mesh)
        # Per-microbatch valid-token weights: head_ce returns a mean over each
        # microbatch's OWN valid tokens, so an unweighted sum/M would give
        # sparse microbatches (uneven -100 padding) outsized per-token gradient
        # weight vs the plain full-batch token-mean. Weight each microbatch's
        # loss (and cotangent seed) by its share of the global valid count.
        valid_ms = jnp.sum(labels_ms != -100, axis=(1, 2)).astype(jnp.float32)
        mb_weight = valid_ms / jnp.maximum(jnp.sum(valid_ms), 1.0)  # [M]

        # ---- the compiled 1F1B schedule over the pipe axis
        def pipe_fn(blocks_w, head_w, xs, labels_ms, mb_weight, side_ms):
            stage = jax.lax.axis_index(PIPE_AXIS)
            T = 2 * (M + S - 1)
            mb_shape = xs.shape[1:]  # [mb, s, d]

            def stage_fwd(wb, h, side_mb, mb_idx):
                def body(carry, w_i):
                    h, i, aux = carry
                    rng_i = None
                    if rng is not None:
                        rng_i = jax.random.fold_in(
                            jax.random.fold_in(rng, stage * L_local + i), mb_idx)
                    fn = pipe_block
                    if cfg.remat:
                        fn = jax.checkpoint(fn, policy=_remat_policy(cfg))
                    h, aux_i = fn(w_i, h, dict(side_mb, **static_side), rng_i)
                    return (h, i + 1, aux + aux_i), None

                (h, _, aux), _ = jax.lax.scan(
                    body,
                    (h, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.float32)),
                    wb)
                return h, aux

            zeros_mb = jnp.zeros(mb_shape, compute_dtype)
            carry0 = {
                "h_recv": zeros_mb,
                "g_recv": jnp.zeros(mb_shape, jnp.float32),
                "buf_h": jnp.zeros((S,) + mb_shape, compute_dtype),
                "buf_side": jax.tree_util.tree_map(
                    lambda a: jnp.zeros((S,) + a.shape[1:], a.dtype), side_ms),
                "gW": jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), blocks_w),
                "g_head": jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), head_w),
                "gx": jnp.zeros((M,) + mb_shape, jnp.float32),
                "loss": jnp.zeros((), jnp.float32),
                "aux": jnp.zeros((), jnp.float32),
            }

            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            aux_cot = (scale / M).astype(jnp.float32)

            def tick(carry, t):
                m_f = jnp.clip((t - stage) // 2, 0, M - 1)
                do_f = (t >= stage) & ((t - stage) % 2 == 0) & ((t - stage) // 2 < M)
                boff = 2 * S - 1 - stage
                m_b = jnp.clip((t - boff) // 2, 0, M - 1)
                do_b = (t >= boff) & ((t - boff) % 2 == 0) & ((t - boff) // 2 < M)

                side_f = jax.tree_util.tree_map(lambda a: a[m_f], side_ms)
                h_in = jnp.where(stage == 0, xs[m_f].astype(compute_dtype),
                                 carry["h_recv"])

                # ---- forward tick: run local layers, bank the stage input
                def fwd_case(ops):
                    buf_h, buf_side = ops
                    h_out, _ = stage_fwd(blocks_w, h_in, side_f, m_f)
                    buf_h = jax.lax.dynamic_update_index_in_dim(
                        buf_h, h_in, m_f % S, 0)
                    buf_side = jax.tree_util.tree_map(
                        lambda b, v: jax.lax.dynamic_update_index_in_dim(
                            b, v, m_f % S, 0), buf_side, side_f)
                    return h_out, buf_h, buf_side

                def no_fwd(ops):
                    buf_h, buf_side = ops
                    return zeros_mb, buf_h, buf_side

                h_out, buf_h, buf_side = jax.lax.cond(
                    do_f, fwd_case, no_fwd, (carry["buf_h"], carry["buf_side"]))

                # ---- backward tick: recompute stage fwd under vjp, chain cotangents
                def bwd_case(ops):
                    gW, g_head, gx, loss_acc, aux_acc = ops
                    h_saved = carry["buf_h"][m_b % S]
                    side_b = jax.tree_util.tree_map(
                        lambda b: b[m_b % S], carry["buf_side"])
                    (h2, aux_v), f_vjp = jax.vjp(
                        lambda wb, h: stage_fwd(wb, h, side_b, m_b),
                        blocks_w, h_saved)

                    def head_case(_):
                        ls, h_vjp = jax.vjp(
                            lambda wh, hh: head_loss(wh, hh, labels_ms[m_b]),
                            head_w, h2)
                        w_m = mb_weight[m_b]
                        g_wh, g_h2 = h_vjp((scale * w_m).astype(ls.dtype))
                        return (jax.tree_util.tree_map(
                                    lambda a: a.astype(jnp.float32), g_wh),
                                g_h2.astype(compute_dtype),
                                (ls * w_m).astype(jnp.float32))

                    def mid_case(_):
                        return (jax.tree_util.tree_map(
                                    lambda a: jnp.zeros(a.shape, jnp.float32),
                                    head_w),
                                carry["g_recv"].astype(compute_dtype),
                                jnp.zeros((), jnp.float32))

                    g_wh, g_h2, ls = jax.lax.cond(stage == S - 1, head_case,
                                                  mid_case, None)
                    g_wb, g_h_in = f_vjp((g_h2, aux_cot))
                    gW = jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(jnp.float32), gW, g_wb)
                    g_head = jax.tree_util.tree_map(jnp.add, g_head, g_wh)
                    gx = jax.lax.dynamic_update_index_in_dim(
                        gx, g_h_in.astype(jnp.float32), m_b, 0)
                    return (gW, g_head, gx, loss_acc + ls, aux_acc + aux_v,
                            g_h_in.astype(jnp.float32))

                def no_bwd(ops):
                    gW, g_head, gx, loss_acc, aux_acc = ops
                    return (gW, g_head, gx, loss_acc, aux_acc,
                            jnp.zeros(mb_shape, jnp.float32))

                gW, g_head, gx, loss_acc, aux_acc, g_send = jax.lax.cond(
                    do_b, bwd_case, no_bwd,
                    (carry["gW"], carry["g_head"], carry["gx"],
                     carry["loss"], carry["aux"]))

                # ---- rotate: activations forward, cotangents backward
                h_recv = jax.lax.ppermute(h_out, PIPE_AXIS, fwd_perm)
                g_recv = jax.lax.ppermute(g_send, PIPE_AXIS, bwd_perm)

                new_carry = {
                    "h_recv": h_recv, "g_recv": g_recv,
                    "buf_h": buf_h, "buf_side": buf_side,
                    "gW": gW, "g_head": g_head, "gx": gx,
                    "loss": loss_acc, "aux": aux_acc,
                }
                return new_carry, None

            carry, _ = jax.lax.scan(tick, carry0, jnp.arange(2 * (M + S - 1)))

            is_last = (stage == S - 1).astype(jnp.float32)
            is_first = (stage == 0).astype(jnp.float32)
            # per-mb losses arrive pre-weighted by valid-token share -> plain sum
            loss = jax.lax.psum(carry["loss"] * is_last, PIPE_AXIS)
            aux = jax.lax.psum(carry["aux"], PIPE_AXIS) / M
            g_head = jax.tree_util.tree_map(
                lambda a: jax.lax.psum(a * is_last, PIPE_AXIS), carry["g_head"])
            gx = jax.lax.psum(carry["gx"] * is_first, PIPE_AXIS)
            return loss, aux, carry["gW"], g_head, gx

        if tp_manual:
            # layers dim over pipe + whatever model-axis layout the engine gave
            # each leaf; axes outside {pipe, model} (e.g. ZeRO's data) stay auto
            manual = (PIPE_AXIS, "model")

            def filt(spec):
                return P(*(a if a in manual else None for a in tuple(spec)))

            blocks_specs = jax.tree_util.tree_map(
                filt, blocks_param_specs, is_leaf=lambda x: isinstance(x, P))
            axis_names = {PIPE_AXIS, "model"}
        else:
            blocks_specs = jax.tree_util.tree_map(lambda _: P(PIPE_AXIS),
                                                  params["blocks"])
            axis_names = {PIPE_AXIS}
        head_specs = jax.tree_util.tree_map(lambda _: P(), head_w)
        side_specs = jax.tree_util.tree_map(lambda _: P(), side_ms)
        # Gather the block weights to exactly their manual-region layout BEFORE
        # entering the schedule: any leftover data-axis (ZeRO-3) sharding would
        # make the auto partitioner emit its all-gathers inside the
        # stage-varying lax.cond branches — a rendezvous deadlock at runtime.
        # (The reference has the same constraint: its pipeline engine composes
        # with ZeRO-1, not ZeRO-3, deepspeed/runtime/pipe/engine.py:61.)
        blocks_in = jax.tree_util.tree_map(
            lambda a, s: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, s)),
            params["blocks"], blocks_specs)
        sm = jax.shard_map(
            pipe_fn,
            mesh=mesh,
            in_specs=(blocks_specs, head_specs, P(), P(), P(), side_specs),
            out_specs=(P(), P(), blocks_specs, head_specs, P()),
            axis_names=axis_names,
            check_vma=False,
        )
        loss, aux_mean, gW, g_head, gx = sm(
            blocks_in, head_w, xs, labels_ms, mb_weight, side_ms)

        (g_embed,) = embed_vjp(gx.reshape((B,) + gx.shape[2:]))

        grads = dict(g_embed)
        grads["blocks"] = gW
        for k, v in g_head.items():
            grads[k] = jax.tree_util.tree_map(jnp.add, grads[k], v) \
                if k in grads else v
        # grads carry the fp16 scale (cotangent seeds were scale/M); the loss
        # accumulator summed plain per-microbatch CE, so it reports unscaled —
        # the engine's fwd_bwd contract (grads scaled, loss plain).
        return loss + aux_mean, grads

    return train_step
