"""Pipeline parallelism over arbitrary user module lists.

Reference ``deepspeed/runtime/pipe/module.py``: ``PipelineModule`` consumes a
list of ``LayerSpec`` / ``TiedLayerSpec`` (``module.py:29,:85``), partitions it
into stages (``_partition_layers``, ``module.py:353`` — ``uniform`` /
``parameters`` / ``type:regex`` / custom), and runs the instruction-list
schedule over torch processes. Here the same user surface compiles to ONE
differentiable XLA program, like the built-in transformer pipeline
(``parallel/pipeline.py``) but without assuming a homogeneous stacked block:

- **partitioning** is the same contiguous balanced split (method names match
  the reference);
- **per-stage parameters** are packed into per-dtype flat buffers of shape
  ``[n_stages, max_len]`` carrying logical axes ``("layers", None)`` — the
  existing sharding rule places them over the ``pipe`` mesh axis, so each
  stage holds only its own (padded) parameters, ZeRO/engine machinery
  unchanged;
- **heterogeneous stage programs** run under ``shard_map`` as a
  ``lax.switch`` on ``axis_index("pipe")`` — each branch statically unpacks
  its stage's parameter structure from the local flat buffer and applies its
  own layer sequence; the GPipe tick loop and ``ppermute`` rotation are the
  ones from ``parallel/pipeline.py``;
- **tied layers** (``TiedLayerSpec``, reference ``module.py:85``) share one
  parameter tree passed replicated across ``pipe``; reverse-mode AD inserts
  the psum of the tied cotangents — the reference's explicit tied-grad
  all-reduce (``pipe/module.py:433 allreduce_tied_weight_gradients``) for
  free.

Static-shape constraints (by construction, not limitation of the schedule):
every INTER-stage boundary must produce the same activation shape/dtype.
Stage 0's raw input and the last stage's head/loss are exempt — the first
stage consumes the raw microbatch, the last stage reduces to a scalar loss
inside its branch, so embeddings and heads live inside the pipeline like the
reference's.

Both schedules run over user lists: GPipe (AD through the tick scan) and
1F1B (``build_1f1b_step`` — the default on pipe-only meshes; per-tick
``jax.vjp`` over the stage switch bounds in-flight activations to O(stages)).
TP/SP meshes fall back to GPipe with a warning — widening the manual region
under a stage-varying switch is the transformer-specialized
``pipeline_1f1b.py``'s job. Compile cost of the switch-vjp program grows
with stage count (every branch is traced twice); deep-S pipelines on the
virtual CPU mesh compile slowly, which is why the unit tests pin parity at
S=2 (incl. M>S ring reuse) and only smoke S=4.
"""

import dataclasses
import inspect
import re
import typing

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .topology import PIPE_AXIS, DATA_AXIS
from ..models.layers import Param
from ..utils.logging import logger


class LayerSpec:
    """One pipeline layer as an (init, apply) pair.

    ``init_fn(rng) -> params`` (a pytree of arrays or ``Param`` leaves);
    ``apply_fn(params, x)`` or ``apply_fn(params, x, rng)`` -> y.
    Reference ``pipe/module.py:29 LayerSpec`` (class + args deferred build).
    """

    def __init__(self, init_fn, apply_fn, name=None):
        self.init_fn = init_fn
        self.apply_fn = apply_fn
        self.name = name or getattr(apply_fn, "__name__", "layer")
        try:
            sig = inspect.signature(apply_fn)
            self.takes_rng = len([
                p for p in sig.parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]) >= 3 or "rng" in sig.parameters
        except (TypeError, ValueError):
            self.takes_rng = False

    def build(self, rng):
        params = self.init_fn(rng)
        # every leaf carries logical axes; plain arrays get replicated axes
        return jax.tree_util.tree_map(
            lambda v: v if isinstance(v, Param) else Param(v, (None,) * np.ndim(v)),
            params, is_leaf=lambda x: isinstance(x, Param))

    def apply(self, params, x, rng=None):
        if self.takes_rng:
            return self.apply_fn(params, x, rng)
        return self.apply_fn(params, x)


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other spec of the same
    ``key`` (reference ``pipe/module.py:85 TiedLayerSpec``; the canonical use
    is input embedding + output head). The first spec with a key builds the
    parameters; later ones reuse them."""

    def __init__(self, key, init_fn, apply_fn, name=None):
        super().__init__(init_fn, apply_fn, name=name or key)
        self.key = key


def partition_balanced(weights, n_parts):
    """Contiguous split of ``weights`` into ``n_parts`` non-empty groups
    minimizing the max group weight (reference ``ds_utils.partition_balanced``
    used by ``module.py:353``). Returns boundary indices of length
    ``n_parts + 1``."""
    n = len(weights)
    if n_parts > n:
        raise ValueError(f"cannot split {n} layers into {n_parts} stages")
    prefix = np.concatenate([[0], np.cumsum(np.asarray(weights, np.float64))])

    def fits(cap):
        bounds, start = [0], 0
        for _ in range(n_parts):
            # furthest end with group weight <= cap, leaving enough layers
            # for the remaining stages
            end = int(np.searchsorted(prefix, prefix[start] + cap, "right")) - 1
            end = min(end, n - (n_parts - len(bounds)))
            if end <= start:
                return None
            bounds.append(end)
            start = end
        return bounds if bounds[-1] == n else None

    lo = float(np.max(weights)) if n else 0.0
    hi = float(prefix[-1])
    best = fits(hi)
    for _ in range(50):  # binary search on capacity
        mid = (lo + hi) / 2
        b = fits(mid)
        if b is not None:
            best, hi = b, mid
        else:
            lo = mid
    assert best is not None
    return best


@dataclasses.dataclass
class PipelineModuleConfig:
    """Engine-facing knobs (duck-typed by ``runtime/engine.py:95-127``)."""

    pipeline_stages: int = 1
    pipeline_microbatches: int = 1
    mesh: typing.Any = None
    compute_dtype: typing.Any = jnp.float32
    remat: bool = False
    # GPipe only for user module lists (see module docstring)
    causal: bool = False
    final_layernorm: bool = False


class PipelineModule:
    """Pipeline-train an arbitrary layer list (reference
    ``pipe/module.py:85 PipelineModule``).

    Args:
      layers: list of ``LayerSpec`` / ``TiedLayerSpec``.
      loss_fn: ``loss_fn(y, batch) -> scalar`` — mean loss of the microbatch;
        receives the last layer's output and the (micro)batch dict.
      partition_method: ``"uniform"`` (equal layer counts), ``"parameters"``
        (balance parameter counts), ``"type:<regex>"`` (balance the count of
        layers whose name matches), or an explicit boundary list like
        ``[0, 3, n]`` (reference ``module.py:374-396``).
      input_key: batch dict key holding the first layer's input.
    """

    def __init__(self, layers, loss_fn, partition_method="parameters",
                 input_key="inputs"):
        if not layers:
            raise ValueError("PipelineModule needs at least one layer")
        self.specs = list(layers)
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.input_key = input_key
        self.config = PipelineModuleConfig()
        self._layouts = None  # static packing metadata, set by init()

    # -- partitioning ------------------------------------------------------------
    def _stage_bounds(self, layer_weights):
        method = self.partition_method
        S = self.config.pipeline_stages
        if isinstance(method, (list, tuple)):
            bounds = list(method)
            if len(bounds) != S + 1 or bounds[0] != 0 or bounds[-1] != len(self.specs) \
                    or any(b >= e for b, e in zip(bounds, bounds[1:])):
                raise ValueError(
                    f"explicit partition {method} must be {S + 1} strictly "
                    f"increasing bounds from 0 to {len(self.specs)}")
            return bounds
        if method == "uniform":
            return partition_balanced([1.0] * len(self.specs), S)
        if method == "parameters":
            return partition_balanced(layer_weights, S)
        if isinstance(method, str) and method.startswith("type:"):
            pat = re.compile(method[len("type:"):], re.IGNORECASE)
            w = [1.0 if pat.search(s.name) else 0.0 for s in self.specs]
            if not any(w):
                raise ValueError(f"partition {method!r} matched no layer names "
                                 f"({[s.name for s in self.specs]})")
            return partition_balanced(w, S)
        raise ValueError(f"unknown partition_method {method!r}")

    # -- init --------------------------------------------------------------------
    def init(self, rng):
        """Build all layer params; with pipeline_stages > 1, pack non-tied
        stage params into per-dtype ``[S, max_len]`` flat buffers whose
        ``("layers", None)`` axes shard them over ``pipe``."""
        tied, layer_params = {}, []
        for i, spec in enumerate(self.specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = spec.build(jax.random.fold_in(rng, i))
                layer_params.append(None)
            else:
                layer_params.append(spec.build(jax.random.fold_in(rng, i)))

        S = self.config.pipeline_stages
        if S <= 1:
            self._layouts = None
            return {"layers": [p if p is not None else {} for p in layer_params],
                    "tied": tied}

        weights = [
            0.0 if p is None else float(sum(
                int(np.prod(l.value.shape))
                for l in jax.tree_util.tree_leaves(
                    p, is_leaf=lambda x: isinstance(x, Param))))
            for p in layer_params]
        bounds = self._stage_bounds(weights)
        self._bounds = bounds

        # pack: per stage, per dtype, the concatenation of raveled leaves (in
        # tree_flatten order); static layout records (dtype, offset, shape,
        # treedef) per layer so each switch branch can unpack its own stage
        layouts, sizes = [], {}
        stage_flat = []
        for s in range(S):
            layer_entries = []
            offsets, chunks = {}, {}
            for li in range(bounds[s], bounds[s + 1]):
                p = layer_params[li]
                if p is None:
                    layer_entries.append(None)
                    continue
                vals, treedef = jax.tree_util.tree_flatten(
                    jax.tree_util.tree_map(
                        lambda x: x.value, p,
                        is_leaf=lambda x: isinstance(x, Param)))
                leaves = []
                for v in vals:
                    dt = jnp.result_type(v).name
                    off = offsets.get(dt, 0)
                    size = int(np.prod(np.shape(v))) if np.ndim(v) else 1
                    leaves.append((dt, off, tuple(np.shape(v))))
                    offsets[dt] = off + size
                    chunks.setdefault(dt, []).append(jnp.ravel(v))
                layer_entries.append((treedef, leaves))
            layouts.append(layer_entries)
            stage_flat.append({
                dt: jnp.concatenate(parts) if parts else None
                for dt, parts in chunks.items()})
            for dt, off in offsets.items():
                sizes[dt] = max(sizes.get(dt, 0), off)

        self._layouts = layouts
        buffers = {}
        for dt, L in sizes.items():
            rows = []
            for s in range(S):
                flat = stage_flat[s].get(dt)
                if flat is None:
                    flat = jnp.zeros((0,), dtype=dt)
                rows.append(jnp.pad(flat, (0, L - flat.shape[0])))
            buffers[dt] = Param(jnp.stack(rows), ("layers", None))
        return {"stages": buffers, "tied": tied}

    # -- application -------------------------------------------------------------
    def _unpack_stage(self, stage_buffers, s):
        """Rebuild stage ``s``'s per-layer param trees from the flat buffers.
        ``stage_buffers[dt]`` is the LOCAL row ``[L]`` (inside shard_map) or
        the global ``[S, L]`` (outside; pass ``s`` to row-select)."""
        out = []
        for entry in self._layouts[s]:
            if entry is None:
                out.append(None)
                continue
            treedef, leaves = entry
            vals = []
            for dt, off, shape in leaves:
                buf = stage_buffers[dt]
                size = int(np.prod(shape)) if shape else 1
                vals.append(jax.lax.dynamic_slice_in_dim(
                    buf, off, size, 0).reshape(shape))
            out.append(jax.tree_util.tree_unflatten(treedef, vals))
        return out

    def _layer_apply(self, spec, params, tied, x, rng, layer_idx):
        p = tied[spec.key] if isinstance(spec, TiedLayerSpec) else params
        if isinstance(p, dict) and not isinstance(spec, TiedLayerSpec) and p == {}:
            p = None
        vals = jax.tree_util.tree_map(
            lambda l: l.value if isinstance(l, Param) else l, p,
            is_leaf=lambda x_: isinstance(x_, Param))
        r = jax.random.fold_in(rng, layer_idx) if rng is not None else None
        fn = spec.apply
        if self.config.remat:
            fn = jax.checkpoint(
                lambda pp, xx, rr: spec.apply(pp, xx, rr), static_argnums=())
            return fn(vals, x, r)
        return fn(vals, x, r)

    def _sequential(self, params, batch, rng):
        """pipe=1 path (also the parity baseline): plain layer chain."""
        x = batch[self.input_key]
        tied = params.get("tied", {})
        for i, spec in enumerate(self.specs):
            x = self._layer_apply(spec, params["layers"][i], tied, x, rng, i)
        return x

    def loss(self, params, batch, deterministic=True, dropout_rng=None, **_):
        rng = None if deterministic else dropout_rng
        S = self.config.pipeline_stages
        if S <= 1:
            y = self._sequential(params, batch, rng)
            return self.loss_fn(y, batch)
        return self._pipelined_loss(params, batch, rng)

    # -- shared pipelined-schedule plumbing (GPipe loss AND the 1F1B step) -------
    def _pipelined_prep(self, params, batch, M, mesh):
        """Everything both pipelined schedules need: boundary shape (checked),
        microbatched/replicated inputs, flat buffers, and the uniform
        per-stage program factory ``prog(s)(local_bufs, tied_vals, h_in,
        raw_x, tail, rng_t) -> (boundary_out, loss_scalar)`` — one signature
        for every stage so ``lax.switch`` (and ``jax.vjp`` over it, for 1F1B)
        drives heterogeneous stages."""
        cfg = self.config
        S = cfg.pipeline_stages
        if mesh is None:
            raise ValueError("pipeline_stages > 1 requires config.mesh")
        x = batch[self.input_key]
        b = x.shape[0]
        if b % M:
            raise ValueError(f"batch {b} not divisible by microbatches {M}")
        tied = params["tied"]
        bounds = self._bounds
        mb = b // M

        def stage_program(s, p_list, tied_vals, h, rng_t):
            for k, li in enumerate(range(bounds[s], bounds[s + 1])):
                h = self._layer_apply(
                    self.specs[li], p_list[k], tied_vals, h, rng_t, li)
            return h

        # boundary shape check: stage programs are heterogeneous, but every
        # inter-stage hand-off must agree (static shapes; the reference's
        # _send_tensor_meta handshake has no XLA equivalent by design).
        # The engine hands loss() the VALUES tree (Param wrappers stripped by
        # split_params_axes); direct module use may still pass Param leaves.
        unwrap = lambda l: l.value if isinstance(l, Param) else l
        stage_params = [
            self._unpack_stage(
                {dt: unwrap(buf)[s] for dt, buf in params["stages"].items()}, s)
            for s in range(S)]
        shapes = []
        cur = jax.eval_shape(lambda a: a[:mb], x)
        for s in range(S):
            cur = jax.eval_shape(
                lambda h, s=s: stage_program(s, stage_params[s], tied, h, None),
                cur)
            shapes.append((cur.shape, cur.dtype))
        boundary = shapes[0]
        for s in range(1, S - 1):
            if shapes[s] != boundary:
                raise ValueError(
                    f"inter-stage activation mismatch: stage 0 emits "
                    f"{boundary}, stage {s} emits {shapes[s]} — pipeline "
                    f"boundaries must have one static shape/dtype (pick "
                    f"partition bounds that cut at uniform points)")
        bshape, bdtype = boundary

        # [b, ...] -> [M, mb, ...], microbatch rows sharded over data
        def to_microbatches(a):
            a = jnp.reshape(a, (M, mb) + a.shape[1:])
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(None, DATA_AXIS)))

        # f32 across the shard_map boundary for replicated (P()) inputs: AD's
        # psum of their cotangent miscompiles in bf16 under the partial-manual
        # partitioner (see parallel/pipeline.py); originals restored inside
        def to_boundary(a):
            return a.astype(jnp.float32) \
                if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != jnp.float32 \
                else a

        batch_ms = jax.tree_util.tree_map(
            lambda a: to_microbatches(to_boundary(a)), dict(batch))
        batch_dtypes = {k: v.dtype for k, v in batch.items()}
        tied_vals_host = jax.tree_util.tree_map(
            unwrap, tied, is_leaf=lambda x_: isinstance(x_, Param))
        tied_b = jax.tree_util.tree_map(to_boundary, tied_vals_host)
        tied_dtypes = jax.tree_util.tree_map(lambda a: a.dtype, tied_vals_host)
        buffers = {dt: unwrap(buf) for dt, buf in params["stages"].items()}

        def make_progs():
            """Per-stage programs with EXPLICIT weight args (so 1F1B can vjp
            w.r.t. them); GPipe partially applies the loop-invariant ones."""
            progs = []
            for s in range(S):
                def run(local_bufs, tied_vals, h_in, mb_in, mb_tail, rng_t,
                        s=s):
                    # mb_in feeds stage 0 (raw input); mb_tail feeds the last
                    # stage's loss — GPipe passes different microbatches (the
                    # tick holds two in flight), 1F1B passes the same one
                    p_list = self._unpack_stage(local_bufs, s)
                    h = stage_program(
                        s, p_list, tied_vals,
                        mb_in[self.input_key] if s == 0 else h_in, rng_t)
                    if s == S - 1:
                        # head output may differ from the boundary shape: the
                        # loss reduces to a scalar inside the branch, and the
                        # rotating slot gets a dummy
                        loss = self.loss_fn(h, mb_tail).astype(jnp.float32)
                        return jnp.zeros(bshape, bdtype), loss
                    return h.astype(bdtype), jnp.zeros((), jnp.float32)

                progs.append(run)
            return progs

        return dict(S=S, M=M, mesh=mesh, bshape=bshape, bdtype=bdtype,
                    batch_ms=batch_ms, batch_dtypes=batch_dtypes,
                    tied_b=tied_b, tied_dtypes=tied_dtypes, buffers=buffers,
                    make_progs=make_progs)

    def _index_mb(self, pp, batch_in, m):
        """Microbatch ``m`` of every batch leaf, original dtypes restored.
        The stage-0 input is ``tail[self.input_key]`` — no separate gather."""
        return {k: jax.lax.dynamic_index_in_dim(a, m, 0, False)
                .astype(pp["batch_dtypes"][k])
                for k, a in batch_in.items()}

    def _sm_specs(self, pp):
        buf_specs = {dt: P(PIPE_AXIS, None) for dt in pp["buffers"]}
        tied_specs = jax.tree_util.tree_map(lambda _: P(), pp["tied_b"])
        batch_specs = jax.tree_util.tree_map(lambda _: P(), pp["batch_ms"])
        return buf_specs, tied_specs, batch_specs

    def _pipelined_loss(self, params, batch, rng):
        cfg = self.config
        pp = self._pipelined_prep(params, batch, cfg.pipeline_microbatches,
                                  cfg.mesh)
        S, M = pp["S"], pp["M"]
        perm = [(i, (i + 1) % S) for i in range(S)]

        def pipe_fn(bufs, tied_in, batch_in):
            stage = jax.lax.axis_index(PIPE_AXIS)
            local = {dt: v[0] for dt, v in bufs.items()}
            tied_vals = jax.tree_util.tree_map(
                lambda a, dt: a.astype(dt), tied_in, pp["tied_dtypes"])
            progs = pp["make_progs"]()
            branches = [
                lambda h_in, raw_x, tail, rng_t, run=run:
                run(local, tied_vals, h_in, raw_x, tail, rng_t)
                for run in progs]
            T = M + S - 1

            def tick(carry, t):
                h_state, losses = carry
                tm = jnp.clip(t, 0, M - 1)
                idx = t - (S - 1)
                cidx = jnp.clip(idx, 0, M - 1)
                mb_in = self._index_mb(pp, batch_in, tm)
                mb_tail = self._index_mb(pp, batch_in, cidx)
                rng_t = None
                if rng is not None:
                    # the stage's in-flight microbatch id is t - stage:
                    # folding it keeps dropout independent per micro-step
                    rng_t = jax.random.fold_in(
                        rng, jnp.clip(t - stage, 0, M - 1))
                h_out, loss_t = jax.lax.switch(
                    stage, branches, h_state, mb_in, mb_tail, rng_t)
                sel = (stage == S - 1) & (idx >= 0)
                cur = jax.lax.dynamic_index_in_dim(losses, cidx, 0, False)
                losses = jax.lax.dynamic_update_index_in_dim(
                    losses, jnp.where(sel, loss_t, cur), cidx, 0)
                h_next = jax.lax.ppermute(h_out, PIPE_AXIS, perm)
                return (h_next, losses), None

            (_, losses), _ = jax.lax.scan(
                tick, (jnp.zeros(pp["bshape"], pp["bdtype"]),
                       jnp.zeros((M,), jnp.float32)),
                jnp.arange(T))
            # only the last stage holds real losses; replicate via psum (f32)
            total = jax.lax.psum(
                jnp.where(stage == S - 1, jnp.sum(losses), 0.0), PIPE_AXIS)
            return total / M

        buf_specs, tied_specs, batch_specs = self._sm_specs(pp)
        sm = jax.shard_map(
            pipe_fn, mesh=pp["mesh"],
            in_specs=(buf_specs, tied_specs, batch_specs),
            out_specs=P(),
            axis_names={PIPE_AXIS},
            check_vma=False,
        )
        return sm(pp["buffers"], pp["tied_b"], pp["batch_ms"])

    def build_1f1b_step(self, mesh, n_microbatches):
        """1F1B schedule over the user layer list (reference
        ``schedule.py:189 TrainSchedule`` — in-flight activations O(stages),
        not O(microbatches)); same tick math as
        ``pipeline_1f1b.build_1f1b_train_step`` but stage programs are the
        uniform-signature ``lax.switch`` branches, so ONE ``jax.vjp`` over the
        switch is each stage's backward. Returns ``train_step(params, batch,
        scale, rng) -> (loss, grads)`` with the engine's fwd_bwd contract
        (grads carry the fp16 scale, loss is plain)."""
        M = int(n_microbatches)

        def train_step(params, batch, scale, rng):
            pp = self._pipelined_prep(params, batch, M, mesh)
            S = pp["S"]
            bshape, bdtype = pp["bshape"], pp["bdtype"]
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def pipe_fn(bufs, tied_in, batch_in):
                stage = jax.lax.axis_index(PIPE_AXIS)
                local = {dt: v[0] for dt, v in bufs.items()}
                tied_vals = jax.tree_util.tree_map(
                    lambda a, dt: a.astype(dt), tied_in, pp["tied_dtypes"])
                progs = pp["make_progs"]()

                def run_switch(lb, tv, h_in, mb, rng_t):
                    # 1F1B: one microbatch per stage per tick — mb serves as
                    # both the stage-0 input and the last-stage loss batch
                    return jax.lax.switch(stage, progs, lb, tv, h_in, mb, mb,
                                          rng_t)

                def mb_rng(m):
                    return jax.random.fold_in(rng, m) if rng is not None \
                        else None

                carry0 = {
                    "h_recv": jnp.zeros(bshape, bdtype),
                    "g_recv": jnp.zeros(bshape, jnp.float32),
                    # ring buffer of S saved stage INPUTS (the only residual;
                    # the backward tick recomputes the stage under vjp)
                    "buf_h": jnp.zeros((S,) + bshape, bdtype),
                    "g_bufs": jax.tree_util.tree_map(
                        lambda a: jnp.zeros(a.shape, jnp.float32), local),
                    "g_tied": jax.tree_util.tree_map(
                        lambda a: jnp.zeros(a.shape, jnp.float32), tied_vals),
                    "loss": jnp.zeros((), jnp.float32),
                }

                def tick(carry, t):
                    # F(s,m) = s + 2m, B(s,m) = 2S-1-s + 2m: opposite parity,
                    # producers one tick before consumers (pipeline_1f1b.py)
                    m_f = jnp.clip((t - stage) // 2, 0, M - 1)
                    do_f = (t >= stage) & ((t - stage) % 2 == 0) \
                        & ((t - stage) // 2 < M)
                    boff = 2 * S - 1 - stage
                    m_b = jnp.clip((t - boff) // 2, 0, M - 1)
                    do_b = (t >= boff) & ((t - boff) % 2 == 0) \
                        & ((t - boff) // 2 < M)

                    mb_f = self._index_mb(pp, batch_in, m_f)
                    h_in = carry["h_recv"]

                    def fwd_case(buf_h):
                        h_out, _ = run_switch(local, tied_vals, h_in, mb_f,
                                              mb_rng(m_f))
                        return h_out, jax.lax.dynamic_update_index_in_dim(
                            buf_h, h_in, m_f % S, 0)

                    def no_fwd(buf_h):
                        return jnp.zeros(bshape, bdtype), buf_h

                    h_out, buf_h = jax.lax.cond(
                        do_f, fwd_case, no_fwd, carry["buf_h"])

                    mb_b = self._index_mb(pp, batch_in, m_b)

                    def bwd_case(ops):
                        g_bufs, g_tied, loss_acc = ops
                        h_saved = carry["buf_h"][m_b % S]
                        (h2, loss_v), f_vjp = jax.vjp(
                            lambda lb, tv, h: run_switch(
                                lb, tv, h, mb_b, mb_rng(m_b)),
                            local, tied_vals, h_saved)
                        is_last = (stage == S - 1)
                        # cotangent seeds: mid stages chain the received
                        # boundary cotangent; the last stage seeds the scalar
                        # loss with scale/M (grads carry the fp16 scale)
                        g_h2 = jnp.where(is_last, jnp.zeros(bshape, h2.dtype),
                                         carry["g_recv"].astype(h2.dtype))
                        g_ls = jnp.where(is_last,
                                         (scale / M).astype(jnp.float32), 0.0)
                        g_lb, g_tv, g_h_in = f_vjp((g_h2, g_ls))
                        g_bufs = jax.tree_util.tree_map(
                            lambda a, g: a + g.astype(jnp.float32),
                            g_bufs, g_lb)
                        g_tied = jax.tree_util.tree_map(
                            lambda a, g: a + g.astype(jnp.float32),
                            g_tied, g_tv)
                        loss_acc = loss_acc + jnp.where(is_last, loss_v, 0.0)
                        return (g_bufs, g_tied, loss_acc,
                                g_h_in.astype(jnp.float32))

                    def no_bwd(ops):
                        g_bufs, g_tied, loss_acc = ops
                        return (g_bufs, g_tied, loss_acc,
                                jnp.zeros(bshape, jnp.float32))

                    g_bufs, g_tied, loss_acc, g_send = jax.lax.cond(
                        do_b, bwd_case, no_bwd,
                        (carry["g_bufs"], carry["g_tied"], carry["loss"]))

                    # rotate: activations forward, cotangents backward (the
                    # two ppermutes run unconditionally — no collectives
                    # inside the conds)
                    new_carry = {
                        "h_recv": jax.lax.ppermute(h_out, PIPE_AXIS, fwd_perm),
                        "g_recv": jax.lax.ppermute(g_send, PIPE_AXIS, bwd_perm),
                        "buf_h": buf_h,
                        "g_bufs": g_bufs, "g_tied": g_tied, "loss": loss_acc,
                    }
                    return new_carry, None

                carry, _ = jax.lax.scan(tick, carry0,
                                        jnp.arange(2 * (M + S - 1)))
                is_last = (stage == S - 1).astype(jnp.float32)
                loss = jax.lax.psum(carry["loss"] * is_last, PIPE_AXIS) / M
                # every stage contributed its own tied-grad partials: the psum
                # IS the reference's tied-weight allreduce
                g_tied = jax.tree_util.tree_map(
                    lambda a: jax.lax.psum(a, PIPE_AXIS), carry["g_tied"])
                # re-lift the stage dim: out_specs P(pipe, ...) concatenates
                # each stage's [1, L] row back into the global [S, L] buffer
                g_bufs = jax.tree_util.tree_map(
                    lambda a: a[None], carry["g_bufs"])
                return loss, g_bufs, g_tied

            buf_specs, tied_specs, batch_specs = self._sm_specs(pp)
            # Gather the weights to exactly their manual-region layout BEFORE
            # entering the schedule: leftover data-axis (ZeRO-3) sharding
            # would make the auto partitioner emit its all-gathers inside the
            # stage-varying lax.cond branches — a rendezvous deadlock at
            # runtime (same constraint as pipeline_1f1b.py's blocks_in)
            buffers_in = {
                dt: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, P(PIPE_AXIS, None)))
                for dt, a in pp["buffers"].items()}
            tied_in = jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, P())), pp["tied_b"])
            sm = jax.shard_map(
                pipe_fn, mesh=pp["mesh"],
                in_specs=(buf_specs, tied_specs, batch_specs),
                out_specs=(P(), {dt: P(PIPE_AXIS, None) for dt in
                                 pp["buffers"]}, tied_specs),
                axis_names={PIPE_AXIS},
                check_vma=False,
            )
            loss, g_bufs, g_tied = sm(buffers_in, tied_in, pp["batch_ms"])
            # fp32 grads in the params' tree structure (the apply step casts
            # to fp32 anyway; structure is what the grad shardings care about)
            return loss, {"stages": g_bufs, "tied": g_tied}

        return train_step
