"""Logical-axis -> PartitionSpec resolution: ZeRO stages and TP as sharding rules.

This module is where the reference's 10k-LoC ZeRO machinery
(``runtime/zero/stage_1_and_2.py``, ``stage3.py``, ``partition_parameters.py``)
collapses into data. In DeepSpeed terms:

- **ZeRO-1** (optimizer-state partitioning, ``stage_1_and_2.py:90`` with
  ``partition_grads=False``): optimizer-state leaves get a PartitionSpec sharded over
  the ``data`` mesh axis; params/grads stay replicated. XLA places the
  gather-after-step the reference issues by hand (``stage_1_and_2.py:1636``).
- **ZeRO-2** (+ gradient partitioning, ``:159``): the gradient-accumulation buffer is
  also data-sharded; XLA emits the bucketed reduce-scatter the reference builds in
  ``average_tensor`` (``:894``).
- **ZeRO-3** (+ param partitioning, ``stage3.py`` + ``partition_parameters.py:601``):
  param leaves themselves are data-sharded; XLA's SPMD partitioner schedules the
  per-layer allgather/release that ``partitioned_param_coordinator.py:230`` does with
  hooks and trace prefetch. Small params stay replicated — the reference's
  "persistent parameters" threshold (``parameter_offload.py:334``).
- **TP** (Megatron-style): logical axes "mlp"/"heads"/"kv"/"vocab" map onto the
  ``model`` mesh axis (column/row parallel linears); XLA inserts the post-row-parallel
  psum the reference codes in ``module_inject/layers.py``.
- **SP** (sequence parallel): activation specs shard the sequence dim over ``seq``.
"""

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .topology import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS
from ..utils.logging import logger

# Tensor-parallel rule table: logical axis name -> mesh axis (None = replicated).
DEFAULT_TP_RULES = {
    "vocab": MODEL_AXIS,
    "heads": MODEL_AXIS,
    "kv": MODEL_AXIS,
    "mlp": MODEL_AXIS,
    "embed": None,
    "layers": PIPE_AXIS,  # scan dim; sharded iff the mesh has a pipe axis > 1
    "seq_table": None,   # learned position table
    "expert": EXPERT_AXIS,  # expert-stacked FFN weights; all_to_all dispatch
    "expert_logits": None,  # router output dim (small; replicated)
}


def _axis_size(mesh, name):
    return mesh.shape.get(name, 1)


def logical_to_physical(axes, shape, mesh, *, tp_rules=None, data_shard=False,
                        min_data_shard_elems=2 ** 11):
    """Resolve one param leaf's logical axes to a PartitionSpec.

    tp mapping first; then if ``data_shard`` (ZeRO-3 for params / ZeRO-1+ for opt
    state), shard the largest still-unsharded non-"layers" dim over ``data`` —
    skipping leaves smaller than ``min_data_shard_elems`` (persistent small params,
    reference ``parameter_offload.py:334``).
    """
    rules = dict(DEFAULT_TP_RULES)
    if tp_rules:
        rules.update(tp_rules)
    spec = []
    for ax_name, dim in zip(axes, shape):
        mesh_axis = rules.get(ax_name)
        if mesh_axis is not None and _axis_size(mesh, mesh_axis) > 1:
            if dim % _axis_size(mesh, mesh_axis) == 0:
                spec.append(mesh_axis)
            else:
                logger.warning(
                    f"TP: dim {ax_name}={dim} not divisible by mesh axis "
                    f"{mesh_axis}={_axis_size(mesh, mesh_axis)}; replicating"
                )
                spec.append(None)
        else:
            spec.append(None)

    data_size = _axis_size(mesh, DATA_AXIS)
    if data_shard and data_size > 1 and int(np.prod(shape)) >= min_data_shard_elems:
        # largest unsharded, divisible, non-layers dim
        candidates = [
            (dim, i)
            for i, (ax_name, dim, s) in enumerate(zip(axes, shape, spec))
            if s is None and ax_name != "layers" and dim % data_size == 0
        ]
        if candidates:
            _, idx = max(candidates)
            spec[idx] = DATA_AXIS
    return P(*spec)


def param_partition_specs(axes_tree, params_shape_tree, mesh, *, zero_stage=0,
                          tp_rules=None, min_data_shard_elems=2 ** 11):
    """Spec tree for the model parameters themselves (data-sharded iff stage 3)."""
    return jax.tree_util.tree_map(
        lambda axes, shape: logical_to_physical(
            axes, shape, mesh, tp_rules=tp_rules, data_shard=(zero_stage >= 3),
            min_data_shard_elems=min_data_shard_elems,
        ),
        axes_tree,
        params_shape_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
    )


def state_partition_specs(axes_tree, params_shape_tree, mesh, *, zero_stage=0,
                          tp_rules=None, min_data_shard_elems=2 ** 11):
    """Spec tree for param-shaped optimizer/grad-accum leaves (data-sharded for the
    relevant stage: opt state >=1, grads >=2, handled by caller passing the flag)."""
    return jax.tree_util.tree_map(
        lambda axes, shape: logical_to_physical(
            axes, shape, mesh, tp_rules=tp_rules, data_shard=(zero_stage >= 1),
            min_data_shard_elems=min_data_shard_elems,
        ),
        axes_tree,
        params_shape_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
    )


def batch_partition_specs(batch_shapes, mesh):
    """Batch dim over data (and expert, which multiplies the dp world in the
    reference's expert-data-parallel layout, ``utils/groups.py:108``); sequence dim
    over seq if present."""
    seq_size = _axis_size(mesh, SEQ_AXIS)
    expert_size = _axis_size(mesh, EXPERT_AXIS)
    data_size = _axis_size(mesh, DATA_AXIS)

    def leaf_spec(shape):
        if expert_size > 1 and shape and shape[0] % (data_size * expert_size) == 0:
            spec = [(DATA_AXIS, EXPERT_AXIS)]
        else:
            spec = [DATA_AXIS]
        if len(shape) >= 2 and seq_size > 1 and shape[1] % seq_size == 0:
            spec.append(SEQ_AXIS)
        return P(*spec)

    return jax.tree_util.tree_map(
        leaf_spec, batch_shapes, is_leaf=lambda x: isinstance(x, tuple)
    )


def named(mesh, spec_tree):
    """Spec tree -> NamedSharding tree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params, mesh, spec_tree):
    """Place an existing (host/replicated) param tree onto the mesh per specs."""
    shardings = named(mesh, spec_tree)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def replicated(mesh):
    return NamedSharding(mesh, P())
