"""``OnDevice`` — reference ``deepspeed/utils/init_on_device.py``.

The reference monkey-patches torch tensor factories so ``with
OnDevice(dtype, device='meta')`` builds a module of meta tensors (shapes
only) or directly on a target GPU. The JAX design splits construction from
materialization, so the two roles land differently:

- ``device="meta"``: a documented shim (like ``zero.Init``). Models here are
  LAZY — ``model.init`` is a function, and ``initialize()`` traces it with
  ``jax.eval_shape`` and materializes straight into the sharded layout, which
  is exactly what meta-device init exists to enable. Inside the context,
  ``OnDevice.eval_shape(fn, *args)`` is provided for explicit shape-only
  builds.
- a concrete device: a thin wrapper over ``jax.default_device`` — arrays
  created inside the context land there.

``dtype``: when given, ``cast(tree)`` casts float leaves (the reference
patches factories to the dtype; here dtype policy belongs to the model
config, so the cast is explicit).
"""

import jax
import jax.numpy as jnp


class OnDevice:
    def __init__(self, dtype=None, device="meta", enabled=True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._ctx = None

    def __enter__(self):
        if self.enabled and self.device != "meta":
            self._ctx = jax.default_device(self.device)
            self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        return False

    @staticmethod
    def eval_shape(fn, *args, **kwargs):
        """Shape-only build (the meta-device role): returns the pytree of
        ShapeDtypeStructs ``fn`` would produce, materializing nothing."""
        return jax.eval_shape(fn, *args, **kwargs)

    def cast(self, tree):
        """Cast float leaves to the context dtype (no-op without one, and a
        no-op when the whole context is disabled, like the reference)."""
        if self.dtype is None or not self.enabled:
            return tree

        def leaf(a):
            if not jnp.issubdtype(jnp.result_type(a), jnp.floating):
                return a
            if isinstance(a, jax.ShapeDtypeStruct):
                # meta-role leaves: re-type the abstract value, keeping its
                # sharding (dropping it would materialize replicated later)
                return jax.ShapeDtypeStruct(
                    a.shape, self.dtype,
                    sharding=getattr(a, "sharding", None))
            return jnp.asarray(a, self.dtype)  # arrays AND python scalars

        return jax.tree_util.tree_map(leaf, tree)
