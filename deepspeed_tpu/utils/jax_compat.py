"""Compatibility shims over jax API drift.

The codebase targets the modern ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., axis_names=..., check_vma=...)`` entry point. Older jaxlibs
(0.4.x, what some rigs bake in) only ship
``jax.experimental.shard_map.shard_map(f, mesh, in_specs, out_specs,
check_rep, auto)``. The translation is mechanical:

- ``check_vma`` (new name) == ``check_rep`` (old name);
- ``axis_names={...}`` (the axes the body is MANUAL over) is the complement
  of the old ``auto`` frozenset (the axes left to the partitioner).

``install()`` publishes the shim as ``jax.shard_map`` when the real one is
missing, so every call site (and tests doing ``from jax import shard_map``)
works unchanged on both generations. On a modern jax it is a no-op.
"""

import jax


def _resolve_mesh(mesh):
    if mesh is None:
        raise TypeError("shard_map compat shim requires an explicit mesh")
    return mesh


def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
              axis_names=None, check_vma=None, check_rep=None, auto=None):
    """``jax.shard_map``-compatible wrapper that also runs on jax 0.4.x.

    Supports the keyword calling convention used across this repo. With
    ``f=None`` returns a decorator (matching the modern API).
    """
    if f is None:
        return lambda fn: shard_map(fn, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs, axis_names=axis_names,
                                    check_vma=check_vma, check_rep=check_rep,
                                    auto=auto)
    native = getattr(jax, "shard_map", None)
    if native is not None and native is not shard_map:
        kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        elif check_rep is not None:
            kw["check_vma"] = check_rep
        return native(f, **kw)

    from jax.experimental.shard_map import shard_map as legacy

    mesh = _resolve_mesh(mesh)
    check = check_vma if check_vma is not None else check_rep
    if auto is None and axis_names is not None:
        # legacy `auto` = the complement of the manual axes. Size-1 axes are
        # dropped from it: they partition nothing, and the legacy partial-
        # manual lowering mishandles them (observed: NaNs in the 1-bit Adam
        # compressed step on a {data: 8, everything-else: 1} mesh).
        auto = frozenset(a for a in mesh.axis_names
                         if a not in frozenset(axis_names)
                         and mesh.shape[a] > 1)
    kw = {}
    if auto:
        kw["auto"] = frozenset(auto)
    return legacy(f, mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=bool(check) if check is not None else True, **kw)


def _axis_size(axis_name):
    """``jax.lax.axis_size`` for jaxlibs that predate it: ``psum`` of a
    concrete 1 is folded statically from the axis environment, so this
    returns a Python int inside shard_map, exactly like the modern API."""
    return jax.lax.psum(1, axis_name)


def _set_mesh(mesh):
    """``jax.set_mesh`` for jaxlibs that predate it, covering the
    ``with jax.set_mesh(mesh): ...`` context-manager idiom: a ``Mesh`` IS a
    context manager on 0.4.x (the legacy ambient-mesh context), so returning
    it verbatim gives the same scoped behavior."""
    return mesh


def install():
    """Make ``jax.shard_map`` / ``jax.lax.axis_size`` / ``jax.set_mesh``
    resolve on jaxlibs that predate them.

    Idempotent; called from ``deepspeed_tpu/__init__`` (and tests/conftest)
    before any module builds a shard_map program.
    """
    if getattr(jax, "shard_map", None) is None:
        jax.shard_map = shard_map
    if getattr(jax.lax, "axis_size", None) is None:
        jax.lax.axis_size = _axis_size
    if getattr(jax, "set_mesh", None) is None:
        jax.set_mesh = _set_mesh
    return jax.shard_map
