"""Retry with exponential backoff for flaky I/O and rendezvous paths.

Checkpoint writes hit network filesystems (GCS fuse, NFS) where transient
``OSError``s are routine, and ``jax.distributed.initialize`` races the
coordinator process coming up on pod restart. Both get the same treatment:
a :class:`RetryPolicy` (attempts, exponential backoff, jitter, exception
filter) applied via :func:`retry_call` or the :func:`retryable` decorator.
"""

import functools
import os
import random
import time

from .logging import logger


class RetryPolicy:
    """max_attempts total tries; delay before retry ``i`` (1-based) is
    ``min(max_delay, base_delay * multiplier**(i-1))`` scaled by up to
    ``jitter`` fractional randomness. ``retry_on`` filters which exception
    types are retried — anything else propagates immediately."""

    def __init__(self, max_attempts=3, base_delay=0.05, multiplier=2.0,
                 max_delay=5.0, jitter=0.25, retry_on=(OSError,), seed=None,
                 retry_if=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.jitter = jitter
        self.retry_on = tuple(retry_on)
        # optional predicate ANDed with the type filter — e.g. match only
        # transient-looking messages so permanent errors surface immediately
        self.retry_if = retry_if
        self._rng = random.Random(seed)

    def excluding(self, *exc_types):
        """Clone of this policy with ``exc_types`` made non-retryable —
        for call sites where an otherwise-retryable type is known terminal
        (composes with any existing ``retry_if``)."""
        prev = self.retry_if
        return RetryPolicy(
            max_attempts=self.max_attempts, base_delay=self.base_delay,
            multiplier=self.multiplier, max_delay=self.max_delay,
            jitter=self.jitter, retry_on=self.retry_on,
            retry_if=lambda exc: not isinstance(exc, exc_types)
            and (prev is None or bool(prev(exc))))

    def delay(self, attempt):
        """Backoff before retry number ``attempt`` (1 = first retry)."""
        base = min(self.max_delay, self.base_delay * self.multiplier ** (attempt - 1))
        return base * (1.0 + self.jitter * self._rng.random())

    def should_retry(self, exc, attempt):
        if attempt >= self.max_attempts or not isinstance(exc, self.retry_on):
            return False
        return self.retry_if is None or bool(self.retry_if(exc))


def retry_call(fn, *args, policy=None, describe=None, on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)`` under ``policy``; re-raises the last
    exception once attempts are exhausted (or immediately for non-retryable
    types). ``on_retry(exc, attempt)`` runs before each sleep."""
    policy = policy or RetryPolicy()
    what = describe or getattr(fn, "__name__", repr(fn))
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except BaseException as exc:
            if not policy.should_retry(exc, attempt):
                raise
            delay = policy.delay(attempt)
            logger.warning("%s failed (attempt %d/%d): %s — retrying in %.2fs",
                           what, attempt, policy.max_attempts, exc, delay)
            if on_retry is not None:
                on_retry(exc, attempt)
            if delay > 0:
                time.sleep(delay)


def retryable(policy=None, describe=None):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy,
                              describe=describe or fn.__qualname__, **kwargs)

        return wrapper

    return deco


def io_retry_policy():
    """Default policy for checkpoint I/O; knobs via env for ops overrides."""
    return RetryPolicy(
        max_attempts=int(os.environ.get("DS_TPU_CKPT_RETRIES", "3")),
        base_delay=float(os.environ.get("DS_TPU_CKPT_BACKOFF", "0.05")),
        retry_on=(OSError,),
    )
