from .logging import logger, log_dist
from .init_on_device import OnDevice
from .retry import RetryPolicy, retry_call, retryable, io_retry_policy
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .tensor_fragment import (
    param_names,
    safe_get_full_fp32_param,
    safe_get_full_grad,
    safe_get_full_optimizer_state,
    safe_set_full_fp32_param,
    safe_set_full_optimizer_state,
)

__all__ = [
    "logger", "log_dist", "OnDevice",
    "RetryPolicy", "retry_call", "retryable", "io_retry_policy",
    "SynchronizedWallClockTimer", "ThroughputTimer",
    "param_names",
    "safe_get_full_fp32_param", "safe_get_full_grad",
    "safe_get_full_optimizer_state", "safe_set_full_fp32_param",
    "safe_set_full_optimizer_state",
]
