from .logging import logger, log_dist
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "SynchronizedWallClockTimer", "ThroughputTimer"]
