"""Rank-aware logging.

TPU-native equivalent of the reference's ``deepspeed/utils/logging.py`` (``log_dist``,
``logger``): the same rank-filtered logging surface, with ranks taken from
``jax.process_index()`` instead of ``torch.distributed``.
"""

import logging
import os
import sys
import functools

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name="deepspeed_tpu", level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
            )
        )
        logger_.addHandler(handler)
    return logger_


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DS_TPU_LOG_LEVEL", "info").lower(), logging.INFO)
)


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the given process ranks (None / [-1] = all ranks).

    Mirrors the reference's ``log_dist`` semantics (deepspeed/utils/logging.py).
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message):
    _warn_once(message)


@functools.lru_cache(None)
def _warn_once(message):
    logger.warning(message)
