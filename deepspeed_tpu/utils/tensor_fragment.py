"""Debug access to full (unsharded) params, grads, and optimizer state.

Reference ``deepspeed/utils/tensor_fragment.py:91-124``:
``safe_get_full_fp32_param`` / ``safe_get_full_grad`` /
``safe_get_full_optimizer_state`` and the ``safe_set_*`` write-back variants —
the APIs users reach for when debugging a sharded run, where naively reading
``param.data`` would see only this rank's fragment.

Torch addresses fragments through the parameter object; here parameters are
pytree leaves, addressed by tree path — ``"wte/weight"``, a ``("wte",
"weight")`` tuple, or list indices as decimal segments (``"blocks/0/w"``).
``param_names(engine)`` enumerates every valid path.

All getters return host numpy arrays of the FULL value regardless of the
engine's sharding (ZeRO-1/2/3 state/grad/param specs, TP axes): a
``device_get`` on a sharded ``jax.Array`` assembles every addressable shard.
Setters re-place the edited value into the leaf's original device sharding.
Single-controller scope: in a multi-host run each process only addresses its
own shards — gather debugging belongs on a one-process mesh (the reference's
APIs similarly require a live partition group to all-gather through).

ZeRO-Offload engines keep fp32 masters and optimizer state host-side in
native/NVMe layouts; the param getter serves them from the device mirror, but
grad/state access raises with a pointer to ``state_for_checkpoint``.
"""

import numpy as np

import jax
import jax.numpy as jnp


def _split(path):
    if isinstance(path, str):
        parts = [p for p in path.split("/") if p]
    elif isinstance(path, (tuple, list)):
        parts = list(path)
    else:
        raise TypeError(f"path must be str or tuple, got {type(path)!r}")
    if not parts:
        raise KeyError("empty parameter path")
    return parts


def _resolve(tree, path):
    """Walk ``tree`` by the segments of ``path``; returns the leaf."""
    node = tree
    for part in _split(path):
        if isinstance(node, dict):
            if part not in node:
                raise KeyError(
                    f"path segment {part!r} not found; available: "
                    f"{sorted(node.keys())}")
            node = node[part]
        elif isinstance(node, (list, tuple)):
            node = node[int(part)]
        else:
            raise KeyError(f"cannot descend into {type(node).__name__} "
                           f"at segment {part!r}")
    return node


def _replace(tree, path, value):
    """Functionally replace the leaf at ``path``; returns a new tree."""
    parts = _split(path)

    def rec(node, i):
        if i == len(parts):
            return value
        part = parts[i]
        if isinstance(node, dict):
            if part not in node:
                raise KeyError(
                    f"path segment {part!r} not found; available: "
                    f"{sorted(node.keys())}")
            out = dict(node)
            out[part] = rec(node[part], i + 1)
            return out
        if isinstance(node, (list, tuple)):
            idx = int(part)
            seq = list(node)
            seq[idx] = rec(seq[idx], i + 1)
            return type(node)(seq) if isinstance(node, tuple) else seq
        raise KeyError(f"cannot descend into {type(node).__name__} "
                       f"at segment {part!r}")

    return rec(tree, 0)


def keypath_str(keypath):
    """jax key-path -> the ``"a/b/c"`` spelling used by every path-addressed
    API here (fragment getters, injection policies, checkpoint names)."""
    segs = []
    for k in keypath:
        if hasattr(k, "key"):
            segs.append(str(k.key))
        elif hasattr(k, "idx"):
            segs.append(str(k.idx))
        else:
            segs.append(str(k))
    return "/".join(segs)


def param_names(engine):
    """Every parameter path of the engine, ``"a/b/c"``-joined."""
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.params)
    return [keypath_str(keypath) for keypath, _ in flat]


def _to_host_full(leaf):
    return np.asarray(jax.device_get(leaf))


def safe_get_full_fp32_param(engine, path):
    """Full fp32 value of the parameter at ``path`` (reference
    ``tensor_fragment.py:109 safe_get_full_fp32_param``). The engine stores
    masters in fp32 (bf16/fp16 are compute dtypes), so this is the master."""
    if getattr(engine, "_offloaded", None) is not None:
        # offload keeps the device mirror in compute dtype; the fp32 master
        # lives host-side inside the offload handler
        return _to_host_full(
            _resolve(engine._offloaded.masters, path)).astype(np.float32)
    return _to_host_full(_resolve(engine.params, path)).astype(np.float32)


def safe_set_full_fp32_param(engine, path, value):
    """Write ``value`` back into the parameter at ``path``, preserving the
    leaf's dtype and device sharding (reference ``safe_set_full_fp32_param``)."""
    old = _resolve(engine.params, path)
    arr = jnp.asarray(value, dtype=old.dtype)
    if arr.shape != old.shape:
        raise ValueError(f"shape mismatch for {path}: param {old.shape}, "
                         f"value {arr.shape}")
    if getattr(engine, "_offloaded", None) is not None:
        # the device tree is only a compute-dtype MIRROR under offload: step()
        # rebuilds it from the host fp32 masters, so a mirror-only write would
        # be silently reverted at the next step and never reach checkpoints —
        # the master is the write target
        off = engine._offloaded
        master = _resolve(off.masters, path)
        host = np.asarray(value, dtype=np.float32)
        if host.shape != master.shape:
            raise ValueError(f"shape mismatch for {path}: master "
                             f"{master.shape}, value {host.shape}")
        if isinstance(master, np.ndarray):
            # native cpu_adam path: the kernels update these buffers in place
            # and _device_params reads them fresh — mutate, don't replace
            np.copyto(master, host)
        else:
            off.masters = _replace(
                off.masters, path, jax.device_put(host, off.cpu))
    placed = jax.device_put(arr, old.sharding)
    engine.params = _replace(engine.params, path, placed)


def safe_get_full_grad(engine, path):
    """Full fp32 gradient at ``path`` as the optimizer would see it, or None
    when no gradient has been accumulated (reference ``safe_get_full_grad``
    returns None outside the backward window).

    Engine accumulation stores ``sum_micro(grad * loss_scale / gas)``;
    dividing by the live loss scale recovers the effective gradient. Only the
    ``forward()/backward()/step()`` API retains gradients — the fused
    ``train_batch`` path consumes them inside one XLA dispatch.
    """
    if getattr(engine, "_offloaded", None) is not None and \
            engine._acc_grads is None:
        raise NotImplementedError(
            "safe_get_full_grad on a ZeRO-Offload engine outside the "
            "backward window: host grads are transient; read them between "
            "backward() and step()")
    if engine._acc_grads is None:
        return None
    leaf = _resolve(engine._acc_grads, path)
    scale = float(engine._scale) if engine.fp16_enabled else 1.0
    return _to_host_full(leaf).astype(np.float32) / scale


_STATE_STEP_KEYS = ("step",)


def _state_trees(engine):
    state = engine.optimizer_state
    if state is None and getattr(engine, "_offloaded", None) is not None:
        # CPU offload keeps the state host-side; the XLA-CPU path exposes the
        # same {"step", "exp_avg", ...} dict. Native/NVMe layouts (in-place
        # numpy / on-disk leaves) have no tree to resolve against.
        state = engine._offloaded.state
        if state is None:
            raise NotImplementedError(
                "optimizer state is in the native/NVMe offload layout; use "
                "engine._offloaded.state_for_checkpoint() to inspect it")
    if not isinstance(state, dict):
        raise TypeError(f"unexpected optimizer state layout: {type(state)!r}")
    return {k: v for k, v in state.items() if k not in _STATE_STEP_KEYS}


def safe_get_full_optimizer_state(engine, path, optim_state_key):
    """Full fp32 optimizer state for the parameter at ``path`` — e.g.
    ``"exp_avg"`` / ``"exp_avg_sq"`` for Adam (reference
    ``safe_get_full_optimizer_state``). Raises KeyError listing the valid
    state keys of the active optimizer."""
    trees = _state_trees(engine)
    if optim_state_key not in trees:
        raise KeyError(f"optimizer has no state {optim_state_key!r}; "
                       f"available: {sorted(trees.keys())}")
    return _to_host_full(
        _resolve(trees[optim_state_key], path)).astype(np.float32)


def safe_set_full_optimizer_state(engine, path, value, optim_state_key):
    """Write ``value`` into the optimizer state tensor for ``path``,
    preserving dtype and sharding (reference ``safe_set_full_optimizer_state``)."""
    trees = _state_trees(engine)
    if optim_state_key not in trees:
        raise KeyError(f"optimizer has no state {optim_state_key!r}; "
                       f"available: {sorted(trees.keys())}")
    old = _resolve(trees[optim_state_key], path)
    arr = jnp.asarray(value, dtype=old.dtype)
    if arr.shape != old.shape:
        raise ValueError(f"shape mismatch for {path}: state {old.shape}, "
                         f"value {arr.shape}")
    placed = jax.device_put(arr, old.sharding)
    full_path = [optim_state_key] + _split(path)
    if engine.optimizer_state is not None:
        engine.optimizer_state = _replace(
            engine.optimizer_state, full_path, placed)
    else:  # CPU-offload: the live tree is the handler's host-side state
        engine._offloaded.state = _replace(
            engine._offloaded.state, full_path, placed)
