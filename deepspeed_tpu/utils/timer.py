"""Wall-clock and throughput timers.

TPU-native equivalent of the reference's ``deepspeed/utils/timer.py``:
``SynchronizedWallClockTimer`` (reference :33) and ``ThroughputTimer`` (reference :137).
On TPU, device synchronization is a ``block_until_ready`` on a dispatched token rather
than a CUDA event pair; timers deliberately avoid forcing synchronization unless asked.
Pass ``sync_fn`` (a zero-arg device fence, e.g. the engine's
``block_until_ready`` hook armed by ``telemetry.device_sync``) to make
``stop()`` measure device *execution* instead of host *dispatch* — under
jax's async dispatch an unsynced fwd/bwd timer mostly measures enqueue.
"""

import time

from .logging import log_dist, logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


# one-time nudge when dispatch-only timings reach the monitor (set back to
# False only by a fresh process)
_UNSYNCED_MONITOR_WARNED = False


class SynchronizedWallClockTimer:
    """Group of named timers (reference ``utils/timer.py:33``).

    ``sync_fn``: optional zero-arg device fence run at every ``stop()``
    (opt-in device-sync mode — ``telemetry.device_sync``). Without it the
    timers time dispatch, which is the historical behavior."""

    class Timer:
        def __init__(self, name, sync_fn=None):
            self.name_ = name
            self.sync_fn = sync_fn
            self.started_ = False
            self.start_time = 0.0
            self.elapsed_ = 0.0
            self.count = 0

        def start(self):
            assert not self.started_, f"timer {self.name_} has already been started"
            self.start_time = time.perf_counter()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, f"timer {self.name_} is not started"
            if self.sync_fn is not None:
                self.sync_fn()
            elapsed = time.perf_counter() - self.start_time
            if reset:
                self.elapsed_ = elapsed
            else:
                self.elapsed_ += elapsed
            self.count += 1
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.elapsed_ = 0.0
            self.count = 0

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self):
            return self.elapsed_ / max(self.count, 1)

    def __init__(self, sync_fn=None):
        self.timers = {}
        self.sync_fn = sync_fn

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name, sync_fn=self.sync_fn)
        return self.timers[name]

    def get_timers(self):
        return self.timers

    def log(self, names, normalizer=1.0, reset=True, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0):
        assert normalizer > 0.0
        return {
            name: self.timers[name].mean() * 1000.0 / normalizer
            for name in names
            if name in self.timers
        }

    def write_events(self, monitor, names, step, normalizer=1.0, reset=True):
        """Emit ``Time/<name>_ms`` monitor events. Warns ONCE per process
        when the timers are unsynced: a dispatch-only fwd/bwd number on a
        dashboard reads like an execution time and mis-attributes the step
        (enable ``telemetry.device_sync`` to fence on the device)."""
        global _UNSYNCED_MONITOR_WARNED
        if monitor is None:
            return
        if self.sync_fn is None and not _UNSYNCED_MONITOR_WARNED:
            _UNSYNCED_MONITOR_WARNED = True
            logger.warning(
                "writing UNSYNCED wall-clock timings to the monitor: these "
                "measure host dispatch, not device execution (jax dispatch "
                "is async). Set telemetry.device_sync=true to fence "
                "timers/spans with block_until_ready.")
        events = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                events.append((f"Time/{name}_ms", ms, step))
        if events:
            monitor.write_events(events)


class ThroughputTimer:
    """Samples/sec tracker (reference ``utils/timer.py:137``)."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None,
                 sync_fn=None):
        self.sync_fn = sync_fn
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            if self.sync_fn is not None:
                self.sync_fn()  # samples/sec over executed steps, not queued
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self.start_time = 0.0
            if global_step and report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.4f}, "
                    f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.4f}"
                )
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / max(self.total_elapsed_time, 1e-12)
        return float("-inf")
