"""Program sanitizer: static HLO/jaxpr lint for the compiled hot programs.

The collective audit (``collectives.py``) proves wire VOLUME and the schedule
audit proves EXPOSURE; this module statically checks the *other* ways a
compiled program silently goes wrong on TPU, over the same
post-SPMD-partitioning HLO snapshot (``compile_with_partitioned_hlo``) plus
the jaxpr:

- **dtype-leak** — f32 compute (dot/convolution) and f32 collectives in a
  program configured bf16/f16, attributing leaked flops/wire bytes per
  instruction. A whole-model upcast (a lost ``compute_dtype`` cast, an
  optimizer touching activations) shows up as the f32 dot-flops fraction
  jumping, long before a chip run would OOM or slow down.
- **donation** — ENTRY parameters not covered by ``input_output_alias``
  whose (dtype, local shape) matches an un-aliased output: XLA must keep
  BOTH the input and the fresh output buffer live, doubling that tensor's
  HBM residency. Params/optimizer state/KV caches are the bytes that matter.
- **transfer** — host↔device traffic reachable inside the step body:
  infeed/outfeed/send/recv and host-callback custom-calls
  (``xla_python_cpu_callback`` & friends), plus host-memory-space (``S(5)``)
  layouts. One stray ``jax.debug.print`` or ``io_callback`` in a hot program
  serializes every step on a host round-trip.
- **sharding** — post-SPMD fully-replicated ENTRY tensors above a size
  threshold (each chip holds the full array), and large all-gathers at ENTRY
  scope, outside the known gather islands (the while-body layer scans): a
  full-parameter gather that escaped the per-layer schedule.
- **recompile-hazard** (jaxpr-level) — large constants baked into the trace
  (bloat the executable; if the value varies per call, every variation is a
  retrace) and Python int/float/bool leaves in a program's example
  arguments (weak-type flapping between ``1.0`` and ``np.float32(1.0)``
  doubles the jit cache; a host scalar also re-uploads every call).
- **peak-HBM estimate** — a liveness walk over the HLO in program order:
  allocate each result at its definition, free each operand after its last
  use, recurse into called computations (while bodies, reducers) as a
  transient at the call site. An *attributed* estimate (which instruction is
  live at the peak) to compare against ``compiled.memory_analysis()``.

Findings are structured (``rule``, ``severity``, ``message``, ``bytes``,
``flops``, location) and fold into ``audit_lowered``'s report as a
``sanitizer`` section; ``check_budgets`` enforces per-rule budgets from
``tools/collective_budgets.json`` (tier-1 on the tiny training preset and
the serving decode program). ``tools/program_lint.py`` is the CLI.
"""

import re

from .collectives import (
    DTYPE_BYTES,
    KINDS,
    _dot_flops,
    _group_size,
    _nbytes,
    _parse_computations,
)

SEVERITIES = ("info", "warning", "error")
SEVERITY_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# wire accounting shared with parse_collectives_by_dtype (ring algorithms)
_WIRE_FACTOR = {
    "all-gather": lambda b, g, frac: b * frac,
    "reduce-scatter": lambda b, g, frac: b * g * frac,
    "all-reduce": lambda b, g, frac: 2 * b * frac,
    "all-to-all": lambda b, g, frac: b * frac,
    "collective-permute": lambda b, g, frac: b,
}

_COMPUTE_OPS = ("dot", "convolution")

# host-callback / host-placement custom-call targets (CPU and TPU spellings)
_HOST_CALL_RE = re.compile(
    r'custom_call_target="([^"]*(?:callback|MoveToHost|MoveToDevice|'
    r'host_compute|HostExecute)[^"]*)"')
_HOST_SPACE_RE = re.compile(r"\{[\d,]*:\s*S\(5\)\}")  # host memory space
_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv")

# the attention-logits einsum (bqhd,bkhd->bhqk) runs f32 on purpose — softmax
# numerics — in every zoo model; programs configured bf16 allowlist it so the
# dtype-leak rule flags real upcasts, not this known island
ATTENTION_F32_ALLOW = ("dtype-leak:bqhd,bkhd->bhqk",)

DEFAULTS = {
    "compute_dtype": "bf16",        # program's configured compute dtype
    "donation_bytes_threshold": 1 << 16,     # 64 KiB: ignore scalar litter
    "donation_error_bytes": 64 << 20,        # >= 64 MiB duplicated -> error
    "replicated_bytes_threshold": 1 << 20,   # 1 MiB per-chip full copy
    "replicated_error_bytes": 256 << 20,
    "entry_gather_bytes_threshold": 1 << 20,
    "const_bytes_threshold": 1 << 20,        # baked-jaxpr-constant floor
    "f32_dot_warn_frac": 0.01,      # one f32 dot >= 1% of dot flops -> warning
    "allow": (),                    # ["rule:substring", ...] demotes to info
}


def finding(rule, severity, message, *, computation=None, instruction=None,
            bytes=0.0, flops=0.0, **extra):
    f = {"rule": rule, "severity": severity, "message": message,
         "bytes": float(bytes), "flops": float(flops)}
    if computation is not None:
        f["computation"] = computation
    if instruction is not None:
        f["instruction"] = instruction
    f.update(extra)
    return f


def _allowed(f, allow):
    """An allowlist entry ``rule:substring`` matches findings of that rule
    whose instruction/computation/message contains the substring."""
    hay = ":".join(str(f.get(k, "")) for k in
                   ("instruction", "computation", "message", "op_name"))
    for entry in allow:
        rule, _, pat = entry.partition(":")
        if rule == f["rule"] and pat in hay:
            return True
    return False


# ---------------------------------------------------------------------------
# HLO structure parsing (entry params, outputs, aliasing)
# ---------------------------------------------------------------------------

_PARAM_RE = re.compile(
    r"^(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\w+)\[([\d,]*)\][^ ]*\s+"
    r"parameter\((\d+)\)(.*)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_SHARDING_RE = re.compile(r"sharding=(\{[^,]*?\}|\{[^{}]*\})")


def _is_replicated(sharding):
    """True when a post-SPMD sharding attribute means every device holds the
    full array: ``{replicated}``, or a device tiling whose tile dims are all
    1 with the devices in the trailing replicated dim."""
    if sharding is None:
        return False
    if "replicated}" in sharding and "last_tile" not in sharding:
        return True
    m = re.search(r"devices=\[([\d,]+)\]", sharding)
    if m and "last_tile_dim_replicate" in sharding:
        dims = [int(d) for d in m.group(1).split(",")]
        return all(d == 1 for d in dims[:-1])
    return False


def _entry_region(hlo):
    """The ENTRY computation's lines (between its header and closing brace)."""
    lines = hlo.splitlines()
    out, in_entry = [], False
    for line in lines:
        s = line.strip()
        if s.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if s.startswith("}"):
                break
            out.append(s)
    return out


def parse_entry_params(hlo):
    """ENTRY parameters with their post-SPMD (= per-chip local) shapes:
    ``{index, name, dtype, dims, bytes, sharding, replicated, op_name}``."""
    params = []
    for s in _entry_region(hlo):
        m = _PARAM_RE.match(s)
        if not m:
            continue
        name, dtype, dims, idx, rest = m.groups()
        sh = _SHARDING_RE.search(rest)
        op = _OPNAME_RE.search(rest)
        params.append({
            "index": int(idx), "name": name, "dtype": dtype, "dims": dims,
            "bytes": _nbytes(dtype, dims),
            "sharding": sh.group(1) if sh else None,
            "replicated": _is_replicated(sh.group(1) if sh else None),
            "op_name": op.group(1) if op else None,
        })
    params.sort(key=lambda p: p["index"])
    return params


def parse_entry_outputs(hlo):
    """Output element shapes of the ENTRY ROOT: ``[(dtype, dims), ...]``."""
    for s in _entry_region(hlo):
        if not s.startswith("ROOT"):
            continue
        eq = s.index("=")
        rhs = s[eq + 1:].strip()
        if rhs.startswith("("):
            depth, end = 0, len(rhs)
            for i, ch in enumerate(rhs):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        end = i
                        break
            return re.findall(r"(\w+)\[([\d,]*)\]", rhs[:end])
        m = re.match(r"(\w+)\[([\d,]*)\]", rhs)
        return [m.groups()] if m else []
    return []


def parse_input_output_alias(hlo):
    """``{param_index: output_index}`` from the HloModule header's
    ``input_output_alias={ {out}: (param, {sub}, kind), ... }`` attribute."""
    header = ""
    for line in hlo.splitlines():
        if line.lstrip().startswith("HloModule"):
            header = line
            break
    key = "input_output_alias={"
    start = header.find(key)
    if start < 0:
        return {}
    i = start + len(key)
    depth, end = 1, len(header)
    for j in range(i, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    body = header[i:end]
    alias = {}
    for m in re.finditer(r"\{([\d,\s]*)\}:\s*\((\d+)", body):
        out_idx = m.group(1).split(",")[0].strip()
        alias[int(m.group(2))] = int(out_idx) if out_idx else 0
    return alias


def _loop_bodies(hlo):
    return set(re.findall(r"body=%?([\w.\-]+)", hlo))


def _entry_name(hlo):
    m = re.search(r"^ENTRY\s+%?([\w.\-]+)", hlo, re.M)
    return m.group(1) if m else "<entry>"


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def rule_dtype_leak(hlo, cfg, loop_trip_count=1):
    """f32 (or f64) compute and collectives in a program configured for a
    narrower dtype. Findings attribute flops (dots/convs) and ring-wire bytes
    (collectives) per instruction; the summary carries the f32 dot-flops
    fraction that the budgets gate."""
    target = cfg["compute_dtype"]
    findings = []
    total_dot_flops = leak_dot_flops = 0.0
    leak_wire = 0.0
    if target in ("f32", "fp32", "float32"):
        wide = ("f64",)
    else:
        wide = ("f32", "f64")
    body_names = _loop_bodies(hlo)
    for comp, instrs in _parse_computations(hlo).items():
        by_name = {i["name"]: i for i in instrs}
        trip = loop_trip_count if comp in body_names else 1
        for i in instrs:
            op = i["opcode"]
            kind = op[:-6] if op.endswith("-start") else op
            if op in _COMPUTE_OPS and i["dtype"] is not None:
                fl = _dot_flops(i, by_name) * trip
                total_dot_flops += fl
                if i["dtype"] in wide:
                    leak_dot_flops += fl
                    opn = _OPNAME_RE.search(i["line"])
                    findings.append(finding(
                        "dtype-leak", "info",
                        f"{i['dtype']} {op} in a {target} program",
                        computation=comp, instruction=i["name"],
                        bytes=_nbytes(i["dtype"], i["dims"]) * trip, flops=fl,
                        op_name=opn.group(1) if opn else None, kind="dot"))
            elif kind in KINDS and i["dtype"] in wide and not \
                    op.endswith("-done"):
                b = _nbytes(i["dtype"], i["dims"])
                g = _group_size(i["line"], 1)
                frac = (g - 1) / g if g > 1 else 1.0
                wire = _WIRE_FACTOR[kind](b, g, frac) * trip
                leak_wire += wire
                findings.append(finding(
                    "dtype-leak", "info",
                    f"{i['dtype']} {kind} wire in a {target} program",
                    computation=comp, instruction=i["name"], bytes=wire,
                    kind="collective"))
    # escalate individually-significant f32 dots: one upcast matmul is a
    # structural leak, not rounding noise
    if total_dot_flops > 0:
        for f in findings:
            if f["flops"] / total_dot_flops >= cfg["f32_dot_warn_frac"]:
                f["severity"] = "warning"
    frac = leak_dot_flops / total_dot_flops if total_dot_flops else 0.0
    return findings, {"f32_dot_flops": leak_dot_flops,
                      "total_dot_flops": total_dot_flops,
                      "f32_dot_flops_frac": frac,
                      "f32_collective_wire_bytes": leak_wire}


def rule_donation(hlo, cfg):
    """ENTRY parameters above the size threshold, not in the module's
    ``input_output_alias`` map, whose (dtype, local dims) matches an output
    element that also has no alias: a donation candidate — the step holds
    input AND output buffers where one would do. ``estimated duplicated
    bytes`` is the sum over candidates (what fixing ``donate_argnums``
    saves in per-chip HBM residency)."""
    params = parse_entry_params(hlo)
    outputs = parse_entry_outputs(hlo)
    alias = parse_input_output_alias(hlo)
    aliased_out = set(alias.values())
    free_outputs = {}
    for idx, (dt, dims) in enumerate(outputs):
        if idx not in aliased_out:
            free_outputs.setdefault((dt, dims), []).append(idx)
    findings = []
    candidate_bytes = aliased_bytes = 0.0
    for p in params:
        if p["index"] in alias:
            aliased_bytes += p["bytes"]
            continue
        slots = free_outputs.get((p["dtype"], p["dims"]))
        if not slots:
            continue
        out_idx = slots.pop(0)  # greedy 1:1 — one output can absorb one input
        if not slots:
            del free_outputs[(p["dtype"], p["dims"])]
        if p["bytes"] < cfg["donation_bytes_threshold"]:
            sev = "info"
        elif p["bytes"] >= cfg["donation_error_bytes"]:
            sev = "error"
        else:
            sev = "warning"
        label = p["op_name"] or p["name"]
        findings.append(finding(
            "donation", sev,
            f"input {label} ({p['dtype']}[{p['dims']}]) is not donated but "
            f"matches un-aliased output #{out_idx} — duplicated HBM "
            f"residency",
            instruction=p["name"], bytes=p["bytes"],
            param_index=p["index"], output_index=out_idx,
            op_name=p["op_name"]))
    cand = sum(f["bytes"] for f in findings
               if f["bytes"] >= cfg["donation_bytes_threshold"])
    return findings, {"undonated_candidate_bytes": cand,
                      "undonated_candidates": len(findings),
                      "aliased_param_bytes": aliased_bytes,
                      "n_aliased_params": len(alias)}


def rule_transfer(hlo):
    """Host↔device traffic inside the program: infeed/outfeed/send/recv
    opcodes, host-callback custom-calls, host-memory-space (S(5)) layouts.
    Always ``error``: one host round-trip serializes every step."""
    findings = []
    for comp, instrs in _parse_computations(hlo).items():
        for i in instrs:
            op = i["opcode"]
            kind = None
            if op.split("-")[0] in _TRANSFER_OPS and not op.endswith("-done"):
                kind = op
            elif op == "custom-call":
                m = _HOST_CALL_RE.search(i["line"])
                if m:
                    kind = f"host callback {m.group(1)}"
            elif _HOST_SPACE_RE.search(i["line"]):
                kind = "host-memory-space tensor"
            if kind:
                findings.append(finding(
                    "transfer", "error",
                    f"{kind} inside the compiled step (host round-trip on "
                    f"the hot path)",
                    computation=comp, instruction=i["name"],
                    bytes=_nbytes(i["dtype"], i["dims"])
                    if i["dtype"] else 0.0))
    return findings, {"transfer_count": len(findings)}


def rule_sharding(hlo, cfg, n_devices):
    """Post-SPMD replication check. Local shapes after partitioning ARE the
    per-chip footprint, so a fully-replicated ENTRY tensor above the
    threshold means every chip holds the whole array. Large all-gathers at
    ENTRY scope (outside the while-body gather islands) are flagged too —
    a full-parameter gather that escaped the per-layer schedule."""
    findings = []
    rep_bytes = 0.0
    for p in parse_entry_params(hlo):
        if not p["replicated"] or p["bytes"] < cfg["replicated_bytes_threshold"]:
            continue
        rep_bytes += p["bytes"]
        sev = "error" if p["bytes"] >= cfg["replicated_error_bytes"] \
            else "warning"
        label = p["op_name"] or p["name"]
        findings.append(finding(
            "sharding", sev,
            f"ENTRY input {label} is fully replicated: each of {n_devices} "
            f"chips holds all {p['bytes'] / 1e6:.1f} MB",
            instruction=p["name"], bytes=p["bytes"], op_name=p["op_name"],
            kind="replicated"))
    entry = _entry_name(hlo)
    bodies = _loop_bodies(hlo)
    entry_gather = 0.0
    for comp, instrs in _parse_computations(hlo).items():
        if comp != entry or comp in bodies:
            continue
        for i in instrs:
            op = i["opcode"]
            if op not in ("all-gather", "all-gather-start") or \
                    i["dtype"] is None:
                continue
            b = _nbytes(i["dtype"], i["dims"])
            if b < cfg["entry_gather_bytes_threshold"]:
                continue
            entry_gather += b
            findings.append(finding(
                "sharding", "warning",
                f"{b / 1e6:.1f} MB all-gather at ENTRY scope, outside the "
                f"per-layer gather islands",
                computation=comp, instruction=i["name"], bytes=b,
                kind="entry-gather"))
    return findings, {"replicated_bytes": rep_bytes,
                      "entry_gather_bytes": entry_gather}


def rule_recompile_hazard(closed_jaxpr=None, example_args=None, cfg=None):
    """jaxpr-level hazards. Large baked constants bloat the executable (and
    every changed value is a full retrace); Python scalar leaves in the
    example arguments flap weak types across the jit cache and re-upload
    from host per call — serving knobs must ride as arrays."""
    cfg = {**DEFAULTS, **(cfg or {})}
    findings = []
    const_bytes = 0.0
    if closed_jaxpr is not None:
        for c in getattr(closed_jaxpr, "consts", ()):
            nbytes = getattr(c, "nbytes", None)
            if nbytes is None:
                shape = getattr(c, "shape", None)
                if shape is None:
                    continue
                n = 1
                for d in shape:
                    n *= int(d)
                try:  # numpy-style dtype names ("float64"), not HLO's "f64"
                    import numpy as _np

                    itemsize = _np.dtype(str(getattr(c, "dtype", "float32"))
                                         ).itemsize
                except (TypeError, ValueError):
                    itemsize = 4
                nbytes = n * itemsize
            if nbytes >= cfg["const_bytes_threshold"]:
                const_bytes += nbytes
                findings.append(finding(
                    "recompile-hazard", "warning",
                    f"{nbytes / 1e6:.1f} MB constant baked into the trace "
                    f"(shape {tuple(getattr(c, 'shape', ()))}): a varying "
                    f"value here retraces the whole program",
                    bytes=nbytes))
    n_scalar = 0
    if example_args is not None:
        import jax

        leaves_paths = jax.tree_util.tree_flatten_with_path(example_args)[0]
        for path, leaf in leaves_paths:
            if isinstance(leaf, (bool, int, float)):
                n_scalar += 1
                findings.append(finding(
                    "recompile-hazard", "warning",
                    f"Python {type(leaf).__name__} argument at "
                    f"{jax.tree_util.keystr(path)}: weak-typed scalar — "
                    f"flaps the jit cache against array-typed calls and "
                    f"re-uploads from host every step; pass a jnp array",
                    arg_path=jax.tree_util.keystr(path)))
    return findings, {"baked_const_bytes": const_bytes,
                      "python_scalar_args": n_scalar}


# ---------------------------------------------------------------------------
# peak-HBM estimator (liveness walk)
# ---------------------------------------------------------------------------

# results that alias/view an operand or are metadata-only: no fresh allocation
_ZERO_ALLOC = {"parameter", "tuple", "get-tuple-element", "bitcast",
               "reshape", "while", "constant", "after-all", "partition-id",
               "replica-id"}
_CALLEE_RE = re.compile(
    r"(?:body|condition|to_apply|calls|true_computation|"
    r"false_computation|branch_computations)=\{?%?([\w.\-]+)")


def estimate_peak_hbm(hlo):
    """Liveness walk over the HLO, in program order per computation:
    allocate each instruction's result at its definition, free each operand
    after its last use, and charge a called computation's own peak as a
    transient at the call site (while bodies, reducers, conditionals).

    Approximations, documented: program order stands in for the scheduler's
    order (XLA may rematerialize or reorder), fusion is not modeled (the
    post-SPMD snapshot is pre-fusion, so this over-counts small elementwise
    temporaries), tuples/reshapes/while results are treated as views, and
    donated inputs are still counted on both sides (the donation rule prices
    that separately). Compare against ``compiled.memory_analysis()`` — the
    value here is the ATTRIBUTION: which instruction sits at the peak."""
    comps = _parse_computations(hlo)
    entry = _entry_name(hlo)
    peaks = {}  # computation -> intermediates-only peak bytes
    entry_peak_at = None

    def callees(line):
        return [m for m in _CALLEE_RE.findall(line)]

    # callees appear before callers in HLO dumps; missing ones cost 0
    for comp, instrs in comps.items():
        last_use = {}
        for idx, i in enumerate(instrs):
            for o in i["operands"]:
                last_use[o] = idx
        live = {}
        live_bytes = peak = 0.0
        peak_at = None
        for idx, i in enumerate(instrs):
            b = _nbytes(i["dtype"], i["dims"]) if i["dtype"] else 0.0
            alloc = 0.0 if i["opcode"] in _ZERO_ALLOC else b
            live[i["name"]] = alloc
            live_bytes += alloc
            transient = sum(peaks.get(c, 0.0) for c in callees(i["line"]))
            if live_bytes + transient > peak:
                peak = live_bytes + transient
                peak_at = i["name"]
            for o in set(i["operands"]):
                if last_use.get(o) == idx and o in live:
                    live_bytes -= live.pop(o)
        peaks[comp] = peak
        if comp == entry:
            entry_peak_at = peak_at

    param_bytes = sum(p["bytes"] for p in parse_entry_params(hlo))
    inter = peaks.get(entry, 0.0)
    return {
        "estimate_bytes": param_bytes + inter,
        "argument_bytes": param_bytes,
        "transient_peak_bytes": inter,
        "peak_instruction": entry_peak_at if entry in peaks else None,
    }


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def summarize(findings):
    counts = {s: 0 for s in SEVERITIES}
    by_rule = {}
    for f in findings:
        if f.get("allowed"):
            continue
        counts[f["severity"]] += 1
        r = by_rule.setdefault(f["rule"], {"count": 0, "bytes": 0.0,
                                           "flops": 0.0})
        r["count"] += 1
        r["bytes"] += f["bytes"]
        r["flops"] += f["flops"]
    max_sev = "none"
    for s in reversed(SEVERITIES):
        if counts[s]:
            max_sev = s
            break
    return {"counts": counts, "by_rule": by_rule, "max_severity": max_sev,
            "n_findings": sum(counts.values())}


def sanitize_hlo(hlo, config=None, n_devices=1, loop_trip_count=1):
    """Run every HLO-level rule over one post-SPMD program snapshot.

    ``config`` overrides :data:`DEFAULTS` (compute_dtype, thresholds, and an
    ``allow`` list of ``rule:substring`` entries that demote known-intentional
    findings to allowed-info). Returns ``{"findings", "summary", <per-rule
    summaries>, "peak_hbm"}``.
    """
    cfg = {**DEFAULTS, **(config or {})}
    findings = []
    summary = {}
    for fs, st in (rule_dtype_leak(hlo, cfg, loop_trip_count),
                   rule_donation(hlo, cfg),
                   rule_transfer(hlo),
                   rule_sharding(hlo, cfg, n_devices)):
        findings.extend(fs)
        summary.update(st)
    for f in findings:
        if _allowed(f, cfg["allow"]):
            f["allowed"] = True
            f["severity"] = "info"
    # allowed findings drop out of EVERY budgeted aggregate (the rule
    # functions sum before the allowlist applies): an allow entry means
    # "declared intentional — do not gate on it", so only the live findings
    # feed the budget keys below
    live = [f for f in findings if not f.get("allowed")]

    def _live(rule, kind=None, field="bytes"):
        return sum(f[field] for f in live if f["rule"] == rule
                   and (kind is None or f.get("kind") == kind))

    summary["f32_dot_flops"] = _live("dtype-leak", "dot", "flops")
    summary["f32_dot_flops_frac"] = (
        summary["f32_dot_flops"] / summary["total_dot_flops"]
        if summary.get("total_dot_flops") else 0.0)
    summary["f32_collective_wire_bytes"] = _live("dtype-leak", "collective")
    summary["replicated_bytes"] = _live("sharding", "replicated")
    summary["entry_gather_bytes"] = _live("sharding", "entry-gather")
    summary["undonated_candidate_bytes"] = sum(
        f["bytes"] for f in live
        if f["rule"] == "donation"
        and f["bytes"] >= cfg["donation_bytes_threshold"])
    summary["transfer_count"] = sum(
        1 for f in live if f["rule"] == "transfer")
    findings.sort(key=lambda f: (-SEVERITY_RANK[f["severity"]], -f["bytes"]))
    return {
        "findings": findings,
        "summary": {**summary, **summarize(findings)},
        "peak_hbm": estimate_peak_hbm(hlo),
        "config": {k: (list(v) if isinstance(v, tuple) else v)
                   for k, v in cfg.items()},
    }


def sanitize_jaxpr(closed_jaxpr, example_args=None, config=None):
    """jaxpr-level rules only (recompile hazards); merge into an HLO report
    with :func:`merge_reports` or consume standalone."""
    cfg = {**DEFAULTS, **(config or {})}
    findings, stats = rule_recompile_hazard(closed_jaxpr, example_args, cfg)
    for f in findings:
        if _allowed(f, cfg["allow"]):
            f["allowed"] = True
            f["severity"] = "info"
    return {"findings": findings, "summary": {**stats,
                                              **summarize(findings)}}


def merge_reports(hlo_report, jaxpr_report):
    """Fold a jaxpr report into an HLO report (one program, two views)."""
    findings = hlo_report["findings"] + jaxpr_report["findings"]
    findings.sort(key=lambda f: (-SEVERITY_RANK[f["severity"]], -f["bytes"]))
    summary = {**hlo_report["summary"], **{
        k: v for k, v in jaxpr_report["summary"].items()
        if k not in ("counts", "by_rule", "max_severity", "n_findings")}}
    summary.update(summarize(findings))
    return {**hlo_report, "findings": findings, "summary": summary}


def sanitize_lowered(lowered, config=None, n_devices=1, loop_trip_count=1):
    """Compile a jax ``Lowered`` via the pass-dump path and sanitize the
    post-SPMD snapshot (the standalone entry point; ``audit_lowered`` embeds
    the same report as its ``sanitizer`` section)."""
    from .collectives import compile_with_partitioned_hlo

    _, hlo = compile_with_partitioned_hlo(lowered)
    return sanitize_hlo(hlo, config, n_devices, loop_trip_count)


def count_at_or_above(findings, severity):
    """Findings at or above ``severity`` (allowed ones excluded) — the
    ``--fail-on`` gate."""
    floor = SEVERITY_RANK[severity]
    return sum(1 for f in findings
               if not f.get("allowed")
               and SEVERITY_RANK[f["severity"]] >= floor)


def check_sanitizer_budgets(san, budget):
    """Violation strings for one ``sanitizer`` budget sub-dict (see
    tools/collective_budgets.json). Called from ``check_budgets``."""
    v = []
    s = san["summary"]
    if "errors_max" in budget and s["counts"]["error"] > budget["errors_max"]:
        v.append(f"sanitizer: {s['counts']['error']} error-severity findings "
                 f"exceed budget {budget['errors_max']} "
                 f"(first: {_first_msg(san, 'error')})")
    if "warnings_max" in budget and \
            s["counts"]["warning"] > budget["warnings_max"]:
        v.append(f"sanitizer: {s['counts']['warning']} warning findings "
                 f"exceed budget {budget['warnings_max']} "
                 f"(first: {_first_msg(san, 'warning')})")
    if "f32_dot_flops_frac_max" in budget and \
            s.get("f32_dot_flops_frac", 0.0) > budget["f32_dot_flops_frac_max"]:
        v.append(f"sanitizer: f32 dot flops are "
                 f"{s['f32_dot_flops_frac']:.3f} of total, over budget "
                 f"{budget['f32_dot_flops_frac_max']} (dtype leak — a "
                 f"compute_dtype cast went missing?)")
    if "undonated_bytes_max" in budget and \
            s.get("undonated_candidate_bytes", 0.0) > \
            budget["undonated_bytes_max"]:
        v.append(f"sanitizer: {s['undonated_candidate_bytes'] / 1e6:.2f} MB "
                 f"of donation-candidate inputs (budget "
                 f"{budget['undonated_bytes_max'] / 1e6:.2f} MB) — "
                 f"donate_argnums regression doubles that HBM residency")
    if "transfer_count_max" in budget and \
            s.get("transfer_count", 0) > budget["transfer_count_max"]:
        v.append(f"sanitizer: {s['transfer_count']} host transfers inside "
                 f"the step (budget {budget['transfer_count_max']}) — a "
                 f"debug callback left on the hot path?")
    if "replicated_bytes_max" in budget and \
            s.get("replicated_bytes", 0.0) > budget["replicated_bytes_max"]:
        v.append(f"sanitizer: {s['replicated_bytes'] / 1e6:.1f} MB of "
                 f"above-threshold replicated ENTRY tensors (budget "
                 f"{budget['replicated_bytes_max'] / 1e6:.1f} MB)")
    if "entry_gather_bytes_max" in budget and \
            s.get("entry_gather_bytes", 0.0) > budget["entry_gather_bytes_max"]:
        v.append(f"sanitizer: {s['entry_gather_bytes'] / 1e6:.1f} MB of "
                 f"ENTRY-scope all-gathers outside the gather islands "
                 f"(budget {budget['entry_gather_bytes_max'] / 1e6:.1f} MB)")
    if "peak_hbm_gb_max" in budget and \
            san["peak_hbm"]["estimate_bytes"] > budget["peak_hbm_gb_max"] * 1e9:
        v.append(f"sanitizer: estimated peak HBM "
                 f"{san['peak_hbm']['estimate_bytes'] / 1e9:.2f} GB/chip "
                 f"exceeds budget {budget['peak_hbm_gb_max']} GB (liveness "
                 f"estimate, peak at {san['peak_hbm']['peak_instruction']})")
    return v


def _first_msg(san, severity):
    for f in san["findings"]:
        if f["severity"] == severity and not f.get("allowed"):
            return f["message"]
    return "?"
