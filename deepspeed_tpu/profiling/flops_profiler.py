"""FLOPs profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` monkey-patches
``torch.nn.functional`` (``wrapFunc:738``) and walks module hooks to count
flops/macs/latency per submodule. Under XLA the compiler itself is the source of
truth: ``Compiled.cost_analysis()`` reports exact flops/bytes for the optimized
HLO — no patching, and fusion effects are included. This module provides:

- ``FlopsProfiler``: profile any jittable fn (cost analysis + measured walltime
  -> achieved FLOP/s and utilization);
- ``transformer_train_flops``: the analytic 6*N + attention formula used for MFU
  accounting (matches the profiler's model-level numbers);
- ``get_model_profile``: reference ``get_model_profile`` shape — params/flops/
  latency summary for a model + batch.
"""

import time

import numpy as np
import jax

from ..utils.logging import logger


class FlopsProfiler:
    """Profile a jitted function: XLA-reported flops + measured latency."""

    def __init__(self, fn):
        self.fn = fn
        self._compiled = None
        self._flops = None

    def compile(self, *args, **kwargs):
        lowered = jax.jit(self.fn).lower(*args, **kwargs)
        self._compiled = lowered.compile()
        cost = self._compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        self._flops = float(cost.get("flops", 0.0)) if cost else 0.0
        self._bytes = float(cost.get("bytes accessed", 0.0)) if cost else 0.0
        return self

    @property
    def flops(self):
        return self._flops

    @property
    def bytes_accessed(self):
        return self._bytes

    def measure(self, *args, n_iters=10, warmup=2, **kwargs):
        """Run the compiled fn; returns dict with flops, latency, achieved FLOP/s."""
        if self._compiled is None:
            self.compile(*args, **kwargs)
        for _ in range(warmup):
            out = self._compiled(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = self._compiled(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n_iters
        return {
            "flops": self._flops,
            "bytes_accessed": self._bytes,
            "latency_s": dt,
            "flops_per_s": self._flops / dt if dt > 0 else 0.0,
        }


def transformer_train_flops(cfg, batch_size, seq_len, include_backward=True,
                            checkpoint_activations=False):
    """Analytic training flops for one step of a causal transformer.

    The standard accounting (also what the reference's profiler effectively sums):
    forward = 2 * N * tokens matmul flops + attention 2*b*h*s^2*dh*2;
    backward = 2x forward; activation recompute adds another forward.
    """
    tokens = batch_size * seq_len
    n_params = cfg.num_params()
    # embedding lookups are gathers; the LM head matmul is vocab*d per token
    matmul = 2 * n_params * tokens
    attn = 4 * batch_size * cfg.n_heads * (seq_len ** 2) * cfg.head_dim * cfg.n_layers
    fwd = matmul + attn
    mult = 1
    if include_backward:
        mult += 2
    if checkpoint_activations:
        mult += 1
    return fwd * mult


def _fmt(n):
    for unit in ["", "K", "M", "G", "T", "P"]:
        if abs(n) < 1000:
            return f"{n:.2f} {unit}"
        n /= 1000.0
    return f"{n:.2f} E"


def get_model_profile(model, batch, *, loss=False, n_iters=5, print_profile=True):
    """Profile a model's forward (or loss) on a batch (reference
    ``flops_profiler.get_model_profile``). Returns (flops, macs, params)."""
    import jax.numpy as jnp

    from ..models import split_params_axes

    params, _ = split_params_axes(model.init(jax.random.PRNGKey(0)))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))

    if loss:
        fn = lambda p: model.loss(p, batch)
        prof = FlopsProfiler(fn).compile(params)
        stats = prof.measure(params, n_iters=n_iters)
    else:
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        fn = lambda p: model.apply(p, jnp.asarray(ids))
        prof = FlopsProfiler(fn).compile(params)
        stats = prof.measure(params, n_iters=n_iters)

    flops = stats["flops"]
    macs = flops / 2
    if print_profile:
        logger.info(
            f"params: {_fmt(n_params)} | flops: {_fmt(flops)} | macs: {_fmt(macs)} "
            f"| latency: {stats['latency_s'] * 1e3:.2f} ms | "
            f"achieved: {_fmt(stats['flops_per_s'])}FLOP/s"
        )
    return flops, macs, n_params
