"""FLOPs profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` monkey-patches
``torch.nn.functional`` (``wrapFunc:738``) and walks module hooks to count
flops/macs/latency per submodule. Under XLA the compiler itself is the source of
truth: ``Compiled.cost_analysis()`` reports exact flops/bytes for the optimized
HLO — no patching, and fusion effects are included. This module provides:

- ``FlopsProfiler``: profile any jittable fn (cost analysis + measured walltime
  -> achieved FLOP/s and utilization);
- ``transformer_train_flops``: the analytic 6*N + attention formula used for MFU
  accounting (matches the profiler's model-level numbers);
- ``get_model_profile``: reference ``get_model_profile`` shape — params/flops/
  latency summary for a model + batch.
"""

import time

import numpy as np
import jax

from ..utils.logging import logger


class FlopsProfiler:
    """Profile a jitted function: XLA-reported flops + measured latency.

    With ``collectives=True`` the compile also captures per-step collective
    wire bytes by kind and payload dtype (``profiling/collectives.py``) — a
    live run then reports wire bytes next to FLOPs. ``collective_trip_count``
    multiplies ops inside ``while`` bodies (pass ``n_layers`` for
    scan-over-layers programs; defaults to 1).
    """

    def __init__(self, fn, collectives=False, collective_trip_count=1):
        self.fn = fn
        self._compiled = None
        self._flops = None
        self._want_collectives = collectives
        self._trip_count = collective_trip_count
        self._collectives = None

    def compile(self, *args, **kwargs):
        lowered = jax.jit(self.fn).lower(*args, **kwargs)
        if self._want_collectives:
            from .collectives import (compile_with_partitioned_hlo,
                                      parse_collectives_by_dtype)

            self._compiled, hlo = compile_with_partitioned_hlo(lowered)
            stats = parse_collectives_by_dtype(
                hlo, jax.device_count(), self._trip_count)
            stats.pop("_loop_body_computations", None)
            self._collectives = stats
        else:
            self._compiled = lowered.compile()
        cost = self._compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        self._flops = float(cost.get("flops", 0.0)) if cost else 0.0
        self._bytes = float(cost.get("bytes accessed", 0.0)) if cost else 0.0
        return self

    @property
    def flops(self):
        return self._flops

    @property
    def bytes_accessed(self):
        return self._bytes

    @property
    def collective_stats(self):
        """Per-kind wire stats (None unless compiled with collectives=True)."""
        return self._collectives

    @property
    def collective_wire_bytes(self):
        """Total collective wire bytes per chip per step (0 when unknown)."""
        if not self._collectives:
            return 0.0
        return sum(s["wire_bytes"] for s in self._collectives.values())

    def measure(self, *args, n_iters=10, warmup=2, **kwargs):
        """Run the compiled fn; returns dict with flops, latency, achieved FLOP/s."""
        if self._compiled is None:
            self.compile(*args, **kwargs)
        for _ in range(warmup):
            out = self._compiled(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = self._compiled(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n_iters
        stats = {
            "flops": self._flops,
            "bytes_accessed": self._bytes,
            "latency_s": dt,
            "flops_per_s": self._flops / dt if dt > 0 else 0.0,
        }
        if self._collectives is not None:
            stats["collective_wire_bytes"] = self.collective_wire_bytes
            stats["collectives"] = self._collectives
        return stats


def transformer_train_flops(cfg, batch_size, seq_len, include_backward=True,
                            checkpoint_activations=False):
    """Analytic training flops for one step of a causal transformer.

    The standard accounting (also what the reference's profiler effectively sums):
    forward = 2 * N * tokens matmul flops + attention 2*b*h*s^2*dh*2;
    backward = 2x forward; activation recompute adds another forward.
    """
    tokens = batch_size * seq_len
    n_params = cfg.num_params()
    # embedding lookups are gathers; the LM head matmul is vocab*d per token
    matmul = 2 * n_params * tokens
    attn = 4 * batch_size * cfg.n_heads * (seq_len ** 2) * cfg.head_dim * cfg.n_layers
    fwd = matmul + attn
    mult = 1
    if include_backward:
        mult += 2
    if checkpoint_activations:
        mult += 1
    return fwd * mult


def _fmt(n):
    for unit in ["", "K", "M", "G", "T", "P"]:
        if abs(n) < 1000:
            return f"{n:.2f} {unit}"
        n /= 1000.0
    return f"{n:.2f} E"


def _profile_forward(model, batch, *, loss=False, n_iters=5):
    """Shared scaffold: init params, compile the forward (or loss), measure.
    Returns (params, stats)."""
    import jax.numpy as jnp

    from ..models import split_params_axes

    params, _ = split_params_axes(model.init(jax.random.PRNGKey(0)))
    if loss:
        fn = lambda p: model.loss(p, batch)
    else:
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        fn = lambda p: model.apply(p, jnp.asarray(ids))
    prof = FlopsProfiler(fn).compile(params)
    return params, prof.measure(params, n_iters=n_iters)


def get_model_profile(model, batch, *, loss=False, n_iters=5, print_profile=True):
    """Profile a model's forward (or loss) on a batch (reference
    ``flops_profiler.get_model_profile``). Returns (flops, macs, params)."""
    params, stats = _profile_forward(model, batch, loss=loss, n_iters=n_iters)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    flops = stats["flops"]
    macs = flops / 2
    if print_profile:
        logger.info(
            f"params: {_fmt(n_params)} | flops: {_fmt(flops)} | macs: {_fmt(macs)} "
            f"| latency: {stats['latency_s'] * 1e3:.2f} ms | "
            f"achieved: {_fmt(stats['flops_per_s'])}FLOP/s"
        )
    return flops, macs, n_params


# ---------------------------------------------------------------------------------
# Per-module breakdown (reference profiler.py:66 print_model_profile: a tree of
# params / MACs / latency per submodule with top-modules aggregation).
#
# The reference collects these with forward hooks on every nn.Module. Under XLA
# the whole model is ONE fused program, so per-module walltime is not separately
# observable from inside it; instead the profile MEASURES prefix programs
# (embedding -> backbone -> full forward) and attributes each stage its
# difference — real wall time, so memory-bound stages (embedding gather, the
# vocab-sized head matmul) no longer inherit GEMM-shaped estimates. Within the
# blocks stage, attn/mlp/ln split the MEASURED blocks time by flops share
# (marked basis="apportioned" — the reference's hook granularity without
# per-op tracing). Params are grouped exactly from the param tree; module rows
# sum to the whole-program totals by construction — pinned by
# tests/unit/test_aux.py.
# ---------------------------------------------------------------------------------


def _measure_stage_latencies(model, params, ids, n_iters, full_ms):
    """Measured wall time for the embedding and backbone prefix programs.

    Returns ``(embed_ms, backbone_ms, full_ms)`` — cumulative, monotone
    (clamped against timer noise). Each prefix is its own jitted program with
    the same shapes, so stage time = difference of adjacent prefixes. The
    full forward is NOT re-measured — ``full_ms`` comes from the
    whole-program measurement the caller already made (re-jitting
    ``model.apply`` here would add a redundant full-size compile).
    """
    import jax.numpy as jnp

    cfg = model.config
    ids = jnp.asarray(ids)

    # token-type injection must mirror what the PROFILED full program does:
    # MaskedLM.apply injects zero segments when type_vocab_size > 0,
    # CausalLM.apply has no token_type path at all — adding the wtt gather to
    # a prefix the full program lacks would overshoot backbone_ms and clamp
    # the head stage to zero
    import inspect

    inject_tt = (getattr(cfg, "type_vocab_size", 0)
                 and "token_type_ids" in inspect.signature(
                     model.apply).parameters)

    def embed_fn(p):
        from ..models import layers as L
        from ..models.transformer import _norm_apply

        x = L.embedding_apply(p["wte"], ids, cfg.compute_dtype)
        s = ids.shape[1]
        if getattr(cfg, "position_embedding", "") == "learned":
            x = x + jnp.take(p["wpe"]["weight"].astype(cfg.compute_dtype),
                             jnp.arange(s), axis=0)[None]
        if inject_tt and "wtt" in p:
            # segment-0 default, matching MaskedLM.apply's injected zeros
            x = x + jnp.take(p["wtt"]["weight"].astype(cfg.compute_dtype),
                             jnp.zeros((s,), jnp.int32), axis=0)[None]
        if getattr(cfg, "embed_layernorm", False) and "ln_emb" in p:
            x = _norm_apply(cfg, p["ln_emb"], x)
        return x

    def backbone_fn(p):
        kw = {"token_type_ids": jnp.zeros_like(ids)} if inject_tt else {}
        return model.backbone(p, ids, **kw)[0]

    out = []
    for fn in (embed_fn, backbone_fn):
        # AOT path (FlopsProfiler), matching how the full program was timed —
        # jit python-dispatch overhead on the prefixes would bias the stage
        # differences on small models
        stats = FlopsProfiler(fn).measure(params, n_iters=n_iters)
        out.append(stats["latency_s"] * 1e3)
    embed_ms, backbone_ms = out
    backbone_ms = max(backbone_ms, embed_ms)
    return embed_ms, backbone_ms, max(full_ms, backbone_ms)


def _module_param_counts(params):
    """Group exact param counts by module path: top-level entries, with the
    stacked ``blocks`` subtree split by submodule (attn/mlp/ln_*)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    counts = {}
    for path, leaf in flat:
        keys = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        if keys[0] == "blocks" and len(keys) > 1:
            name = f"blocks/{keys[1]}"
        else:
            name = keys[0]
        counts[name] = counts.get(name, 0) + int(np.prod(leaf.shape))
    return counts


def _module_flops(cfg, batch_size, seq_len, param_names=()):
    """Analytic forward flops per module (2*in*out per matmul output element).

    Embedding lookups are gathers (0 MACs, as the reference counts them); the
    LM-head matmul is attributed to ``lm_head`` even when tied to ``wte``.
    ``param_names`` (from the real tree) switches on rows for model variants
    the config alone can't see (MaskedLM's mlm head).
    """
    T = batch_size * seq_len
    d = cfg.d_model
    q_dim = cfg.n_heads * cfg.head_dim
    kv_dim = cfg.kv_heads * cfg.head_dim
    L = cfg.n_layers
    attn_proj = 2 * T * d * (q_dim + 2 * kv_dim) + 2 * T * q_dim * d
    attn_core = 4 * T * seq_len * cfg.n_heads * cfg.head_dim
    if cfg.n_experts > 0:
        # counts what the PROFILED forward executes: model.apply runs
        # deterministic gating, whose default eval capacity is drop-free
        # (C = s), so every expert processes E*b*C slots regardless of top_k
        # (moe/sharded_moe.py moe_mlp_apply)
        E = cfg.n_experts
        if cfg.moe_eval_capacity_factor and cfg.moe_eval_capacity_factor > 0:
            from ..moe.sharded_moe import expert_capacity

            C = expert_capacity(seq_len, E, cfg.moe_top_k,
                                cfg.moe_eval_capacity_factor,
                                cfg.moe_min_capacity)
        else:
            C = seq_len
        slots = batch_size * E * C
        n_expert_matmuls = 3 if cfg.activation == "swiglu" else 2
        mlp = 2 * T * d * E                                  # router
        mlp += n_expert_matmuls * 2 * slots * d * cfg.d_ff   # expert compute
        mlp += 2 * 2 * T * E * C * d                         # dispatch+combine einsums
        if cfg.moe_use_residual:
            n_res_matmuls = 3 if cfg.activation == "swiglu" else 2
            mlp += n_res_matmuls * 2 * T * d * cfg.d_ff + 2 * T * d * 2
    else:
        mlp = 2 * 2 * T * d * cfg.d_ff
        if cfg.activation == "swiglu":
            mlp += 2 * T * d * cfg.d_ff
    norm = 5 * T * d
    flops = {
        "wte": 0.0,
        "blocks/attn": float(L * (attn_proj + attn_core)),
        "blocks/mlp": float(L * mlp),
        "blocks/ln_1": float(L * norm),
        "blocks/ln_2": float(L * norm),
        "lm_head": float(2 * T * d * cfg.vocab_size),
    }
    if getattr(cfg, "position_embedding", "") == "learned":
        flops["wpe"] = 0.0
    if getattr(cfg, "final_layernorm", True):
        flops["ln_f"] = float(norm)
    if "mlm_transform" in param_names:
        # MaskedLM head: dense d->d transform + gelu + LN + output bias add —
        # without these rows the measured head stage would be attributed
        # entirely to lm_head (the only head peer with flops)
        flops["mlm_transform"] = float(2 * T * d * d)
        flops["mlm_ln"] = float(norm)
        flops["mlm_bias"] = float(T * cfg.vocab_size)
    return flops


def get_module_profile(model, batch, *, n_iters=5, print_profile=True):
    """Per-module params/flops/latency breakdown + whole-program totals
    (reference ``print_model_profile`` role).

    Returns ``{"modules": {name: {params, flops, macs, latency_ms, flops_pct}},
    "total": {params, flops, macs, latency_ms, xla_flops}}`` where the module
    flops/params sum EXACTLY to the totals row.
    """
    ids = batch["input_ids"] if isinstance(batch, dict) else batch
    b, s = np.asarray(ids).shape
    params, stats = _profile_forward(model, batch, n_iters=n_iters)
    latency_ms = stats["latency_s"] * 1e3

    param_counts = _module_param_counts(params)
    flops = _module_flops(model.config, b, s, param_names=set(param_counts))
    names = sorted(set(param_counts) | set(flops))
    total_flops = sum(flops.values())

    # measured stage times: embedding, blocks (backbone - embed; ln_f rides
    # here, its flops share is noise), head (full - backbone)
    try:
        embed_ms, backbone_ms, full_ms = _measure_stage_latencies(
            model, params, ids, n_iters, full_ms=latency_ms)
        # stages sum to full_ms; rescale to the canonical whole-program
        # latency so module rows keep summing EXACTLY to the totals row even
        # when timer noise made the clamped full_ms differ from latency_ms
        scale = latency_ms / full_ms if full_ms else 1.0
        stage_ms = {"embed": embed_ms * scale,
                    "blocks": (backbone_ms - embed_ms) * scale,
                    "head": (full_ms - backbone_ms) * scale}
        measured = True
    except Exception as e:  # non-transformer model: flops-share fallback
        logger.warning(f"stage measurement unavailable ({e}); "
                       "falling back to flops-share latency attribution")
        stage_ms = None
        measured = False

    def stage_of(name):
        if name in ("wte", "wpe", "wtt", "ln_emb"):
            return "embed"
        if name.startswith("blocks") or name == "ln_f":
            return "blocks"
        return "head"  # lm_head / mlm_* / pooler

    blocks_flops = sum(f for n, f in flops.items() if stage_of(n) == "blocks")
    modules = {}
    for name in names:
        f = flops.get(name, 0.0)
        share = f / total_flops if total_flops else 0.0
        if stage_ms is None:
            lat, basis = latency_ms * share, "apportioned"
        elif stage_of(name) == "blocks":
            # split the MEASURED blocks stage by flops share
            bshare = f / blocks_flops if blocks_flops else 0.0
            lat, basis = stage_ms["blocks"] * bshare, "apportioned"
        else:
            # embed/head stages: measured; split within the stage by flops
            # first (the tied lm_head owns the head matmul's flops but zero
            # params — param-first weighting would zero the dominant row
            # whenever any peer has params, e.g. MaskedLM's mlm_transform),
            # falling back to params for all-gather stages (wte/wpe: no
            # flops), then to an even split
            stage = stage_of(name)
            peers = [n for n in names if stage_of(n) == stage]
            weights = {n: flops.get(n, 0.0) for n in peers}
            if not any(weights.values()):
                weights = {n: float(param_counts.get(n, 0)) for n in peers}
            if not any(weights.values()):
                weights = {n: 1.0 for n in peers}
            lat = stage_ms[stage] * weights[name] / sum(weights.values())
            basis = "measured-stage"
        modules[name] = {
            "params": param_counts.get(name, 0),
            "flops": f,
            "macs": f / 2,
            "latency_ms": lat,
            "flops_pct": 100.0 * share,
            "basis": basis,
        }
    total = {
        "params": sum(param_counts.values()),
        "flops": total_flops,
        "macs": total_flops / 2,
        "latency_ms": latency_ms,
        "xla_flops": stats["flops"],  # the compiler's own count, for reference
    }
    if measured:
        total["stage_latency_ms"] = {k: round(v, 3)
                                     for k, v in stage_ms.items()}
    if print_profile:
        top = sorted(modules.items(), key=lambda kv: -kv[1]["latency_ms"])
        lines = [f"{'module':<14} {'params':>10} {'flops':>10} {'lat ms':>8} "
                 f"{'%':>6}  basis"]
        for name, m in top:
            lines.append(f"{name:<14} {_fmt(m['params']):>10} {_fmt(m['flops']):>10} "
                         f"{m['latency_ms']:>8.2f} {m['flops_pct']:>5.1f}%  "
                         f"{m['basis']}")
        how = ("stages measured via prefix programs"
               if measured else "latency attributed by flops share")
        lines.append(f"{'TOTAL':<14} {_fmt(total['params']):>10} "
                     f"{_fmt(total['flops']):>10} {latency_ms:>8.2f} {'100.0%':>6} "
                     f"({how}; xla counted {_fmt(total['xla_flops'])}flops)")
        logger.info("\n".join(lines))
    return {"modules": modules, "total": total}
