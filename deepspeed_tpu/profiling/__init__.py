from .flops_profiler import (FlopsProfiler, get_model_profile,
                             get_module_profile, transformer_train_flops)

__all__ = ["FlopsProfiler", "get_model_profile", "get_module_profile",
           "transformer_train_flops"]
