from .collectives import (audit_lowered, check_budgets,
                          compile_with_partitioned_hlo,
                          parse_collectives_by_dtype)
from .flops_profiler import (FlopsProfiler, get_model_profile,
                             get_module_profile, transformer_train_flops)
from .sanitizer import (sanitize_hlo, sanitize_jaxpr, sanitize_lowered,
                        merge_reports, count_at_or_above)

__all__ = ["FlopsProfiler", "get_model_profile", "get_module_profile",
           "transformer_train_flops", "parse_collectives_by_dtype",
           "compile_with_partitioned_hlo", "audit_lowered", "check_budgets",
           "sanitize_hlo", "sanitize_jaxpr", "sanitize_lowered",
           "merge_reports", "count_at_or_above"]
