"""HLO collective-bytes accounting (by kind AND payload dtype).

The compiler is the source of truth for wire traffic, the same way it is for
flops (``flops_profiler.py``): every collective in the compiled step is
parsed out of the HLO with its payload dtype, and ring-algorithm wire costs
are attributed per chip per step. Used by:

- ``tools/collective_audit.py`` — the CI gate that keeps fp32 master
  gathers from silently reappearing on the ZeRO-3 hot path;
- ``FlopsProfiler`` (``collectives=True``) — live wire-bytes alongside
  flops;
- ``DeepSpeedEngine.collective_wire_stats`` — monitor events for training
  runs (``comms_logger.enabled``).

Why the post-partitioning snapshot: the CPU backend's float-normalization
pass legalizes bf16 collectives to f32 + converts (CPU has no native bf16),
so the backend-optimized HLO shows fp32 gathers regardless of what the
program pinned. The snapshot taken right after the SPMD partitioner — via
XLA's pass-dump machinery, per-compile — is the platform-independent SPMD
program a TPU receives, with the partitioner's committed wire dtypes.
(int8 payloads survive even the CPU pipeline: integer collectives are not
float-normalized — a useful cross-check.)
"""

import glob
import os
import re
import shutil
import tempfile

DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "s64": 8, "u64": 8, "s8": 1, "u8": 1, "pred": 1, "s16": 2,
               "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 0.5, "u4": 0.5}

_RESULT_RE = re.compile(r"=\s+(?:\()?(\w+)\[([\d,]*)\]")
_TUPLE_SHAPES_RE = re.compile(r"(\w+)\[([\d,]*)\]")
KINDS = ("all-gather", "reduce-scatter", "all-reduce", "all-to-all",
         "collective-permute")


def _nbytes(dtype, dims):
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * DTYPE_BYTES.get(dtype, 4)


def _result_shape(line, is_start=False):
    """(dtype, dims) of the op's RESULT. Async ``-start`` ops return a tuple
    ``(operand, ..., output)`` — the output (last element) is the
    gathered/reduced result; counting the first would skew all-gather ~N x."""
    if is_start:
        head = line.split("-start(")[0]
        shapes = _TUPLE_SHAPES_RE.findall(head)
        return shapes[-1] if shapes else None
    m = _RESULT_RE.search(line)
    return m.groups() if m else None


_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _group_size(line, default_n):
    """Ring size of THIS op: the replica-group size from the op's
    ``replica_groups`` attribute, not the global device count. On a
    multi-axis mesh a ZeRO reduce-scatter spans only the ``data`` group —
    charging it the full device product would overreport by the non-data
    mesh factor. Explicit list form ``{{0,1,..},..}`` and iota form
    ``[groups,size]<=[N]`` are both parsed; absent/empty groups mean
    all devices."""
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2))
    return default_n


def parse_collectives_by_dtype(hlo, n_devices, loop_trip_count=1):
    """Per-chip wire bytes for each collective kind, split by payload dtype.

    Wire accounting (ring algorithms, per chip, with G = the op's OWN
    replica-group size, falling back to ``n_devices`` when the op carries no
    groups): all-gather receives (G-1)/G of the full result; reduce-scatter
    sends (G-1)/G of the full input (= result x G); all-reduce is RS+AG =
    2 x (G-1)/G x full; all-to-all moves (G-1)/G of its payload;
    collective-permute moves its payload once.

    Ops inside a ``while`` body appear ONCE in the text but run once per
    iteration — multiplied by ``loop_trip_count`` (= n_layers for the layer
    scan; the same static-text trap that broke the r4 autotuner cost model).
    Documented approximation: every while in the audited programs is a layer
    scan (the audit runs with gradient accumulation 1).
    """
    body_names = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    stats = {k: {"count": 0, "wire_bytes": 0.0, "by_dtype": {},
                 "by_computation": {}} for k in KINDS}
    comp = "<entry>"
    for line in hlo.splitlines():
        s = line.strip()
        # computation headers, both HLO text styles: the full signature form
        # `%name (p: ...) -> type {` and the pass-dump compact form `name {`
        if s.endswith("{") and "=" not in s and not s.startswith("ROOT"):
            m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*[({]", s)
            if m and m.group(1) not in ("if", "while", "true", "false"):
                comp = m.group(1)
            continue
        for kind in stats:
            if f" {kind}(" in s or f" {kind}-start(" in s:
                shape = _result_shape(s, is_start=f" {kind}-start(" in s)
                if shape is None:
                    break
                dtype, dims = shape
                b = _nbytes(dtype, dims)
                g = _group_size(s, n_devices)
                frac = (g - 1) / g if g > 1 else 1.0
                if kind == "all-gather":
                    wire = b * frac
                elif kind == "reduce-scatter":
                    wire = b * g * frac
                elif kind == "all-reduce":
                    wire = 2 * b * frac
                elif kind == "all-to-all":
                    wire = b * frac
                else:  # collective-permute
                    wire = b
                if comp in body_names:
                    wire *= loop_trip_count
                st = stats[kind]
                st["count"] += 1
                st["wire_bytes"] += wire
                st["by_dtype"][dtype] = st["by_dtype"].get(dtype, 0.0) + wire
                st["by_computation"][comp] = \
                    st["by_computation"].get(comp, 0) + 1
                break
    stats["_loop_body_computations"] = sorted(body_names)
    return stats


# --------------------------------------------------------------------------
# exposed-vs-overlappable schedule audit
# --------------------------------------------------------------------------

_OPCODE_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[a-z0-9]+\[[\d,]*\](?:\{[^}]*\})?)\s+([\w\-]+)\(")
_NAME_RE = re.compile(r"^(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_COMPUTE_OPS = ("dot", "convolution")


def _operand_names(line):
    """Operand instruction names of an HLO instruction line. Handles both
    text styles: signature form carries operand shapes
    (``all-gather(bf16[128,64] %x)``), the pass-dump compact form carries
    bare names (``all-gather(q.1), channel_id=1``)."""
    m = _OPCODE_RE.search(line)
    if not m:
        return []
    start = line.index("(", m.start(1))
    depth, end = 0, len(line)
    for i in range(start, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    names = []
    for chunk in line[start + 1:end].split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        tok = chunk.split()[-1]  # "bf16[8,8] %x" -> "%x"; "q.1" -> "q.1"
        tok = tok.lstrip("%")
        # constants / literals ("true", "0.5", "{...}") aren't operands we
        # can resolve; harmless to include — they just miss the symbol table
        names.append(tok)
    return names


def _parse_computations(hlo):
    """HLO text -> {computation: [instr, ...]} where each instr is
    ``{"name", "opcode", "operands", "dtype", "dims"}`` in program order.
    Same header heuristics as ``parse_collectives_by_dtype``."""
    comps = {}
    comp = "<entry>"
    for line in hlo.splitlines():
        s = line.strip()
        if s.endswith("{") and "=" not in s and not s.startswith("ROOT"):
            m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*[({]", s)
            if m and m.group(1) not in ("if", "while", "true", "false"):
                comp = m.group(1)
            continue
        nm = _NAME_RE.match(s)
        op = _OPCODE_RE.search(s)
        if not (nm and op):
            continue
        opcode = op.group(1)
        shape = _result_shape(s, is_start=opcode.endswith("-start"))
        comps.setdefault(comp, []).append({
            "name": nm.group(1), "opcode": opcode,
            "operands": _operand_names(s),
            "dtype": shape[0] if shape else None,
            "dims": shape[1] if shape else None,
            "line": s,
        })
    return comps


def _elems(dims):
    n = 1
    for d in (dims or "").split(","):
        if d:
            n *= int(d)
    return n


def _dot_flops(instr, by_name):
    """Flops proxy for a dot/conv: ``2 * sqrt(|lhs| * |rhs| * |result|)``
    — exact ``2*M*K*N`` for a plain matmul (overcounts batched dots by
    ``sqrt(B)``, fine for an is-there-compute-to-hide-behind signal). Falls
    back to ``2 * |result|`` when an operand's shape is unknown."""
    res = _elems(instr["dims"])
    ops = [by_name.get(o) for o in instr["operands"][:2]]
    if len(ops) == 2 and all(o is not None and o["dims"] is not None
                             for o in ops):
        import math

        return 2.0 * math.sqrt(
            max(_elems(ops[0]["dims"]), 1) * max(_elems(ops[1]["dims"]), 1)
            * max(res, 1))
    return 2.0 * res


def _reachable(start_names, adjacency):
    """BFS closure over an adjacency dict name -> [names]."""
    seen = set(start_names)
    frontier = list(start_names)
    while frontier:
        nxt = []
        for n in frontier:
            for m in adjacency.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    nxt.append(m)
        frontier = nxt
    return seen


def audit_schedule(hlo, n_devices, loop_trip_count=1):
    """Classify every collective's wire bytes as *exposed* vs
    *overlappable-behind-compute* by walking the post-SPMD HLO dependency
    graph (ROADMAP item 4's instrument).

    Per collective C (sync op, or an async ``-start``/``-done`` pair merged
    into one node): compute ops (dot/convolution) in the same computation
    that are neither ancestors of C's start nor descendants of C's done are
    *independent* — the scheduler MAY run them concurrently with the wire
    transfer. A collective with no independent compute is **exposed**: every
    flop in its computation either feeds it or waits on it, so its wire time
    lands on the critical path no matter how the backend schedules. This is
    a dependence-structure bound, not a schedule simulation: "overlappable"
    means the graph admits overlap (reported with the independent-flops
    headroom), not that the backend achieved it.

    Wire bytes per op use the same ring accounting (+ while-body trip
    multiplication) as ``parse_collectives_by_dtype``.
    """
    body_names = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    comps = _parse_computations(hlo)
    by_kind = {k: {"exposed_bytes": 0.0, "overlappable_bytes": 0.0,
                   "exposed_count": 0, "overlappable_count": 0}
               for k in KINDS}
    ops = []
    for comp, instrs in comps.items():
        by_name = {i["name"]: i for i in instrs}
        consumers = {}
        for i in instrs:
            for o in i["operands"]:
                if o in by_name:
                    consumers.setdefault(o, []).append(i["name"])
        producers = {i["name"]: [o for o in i["operands"] if o in by_name]
                     for i in instrs}
        trip = loop_trip_count if comp in body_names else 1

        for i in instrs:
            kind = i["opcode"][:-6] if i["opcode"].endswith("-start") \
                else i["opcode"]
            if kind not in KINDS:
                continue  # (-done ops land here too: accounted at -start)
            if i["dtype"] is None:
                continue
            b = _nbytes(i["dtype"], i["dims"])
            g = _group_size(i["line"], n_devices)
            frac = (g - 1) / g if g > 1 else 1.0
            if kind == "all-gather":
                wire = b * frac
            elif kind == "reduce-scatter":
                wire = b * g * frac
            elif kind == "all-reduce":
                wire = 2 * b * frac
            elif kind == "all-to-all":
                wire = b * frac
            else:
                wire = b
            wire *= trip

            # merge an async start with its done: the overlap window is
            # everything not upstream of the start nor downstream of the done
            sinks = [i["name"]]
            if i["opcode"].endswith("-start"):
                for j in instrs:
                    if j["opcode"].endswith("-done") \
                            and i["name"] in j["operands"]:
                        sinks.append(j["name"])
                        break
            ancestors = _reachable([i["name"]], producers)
            descendants = _reachable(sinks, consumers)
            blocked = ancestors | descendants
            indep_flops = sum(
                _dot_flops(j, by_name) * trip for j in instrs
                if j["opcode"] in _COMPUTE_OPS and j["name"] not in blocked)
            exposed = indep_flops <= 0.0
            st = by_kind[kind]
            if exposed:
                st["exposed_bytes"] += wire
                st["exposed_count"] += 1
            else:
                st["overlappable_bytes"] += wire
                st["overlappable_count"] += 1
            ops.append({
                "name": i["name"], "computation": comp, "kind": kind,
                "dtype": i["dtype"], "wire_bytes": wire,
                "async": i["opcode"].endswith("-start"),
                "exposed": exposed,
                "independent_compute_flops": indep_flops,
            })

    exposed_total = sum(s["exposed_bytes"] for s in by_kind.values())
    overlap_total = sum(s["overlappable_bytes"] for s in by_kind.values())
    total = exposed_total + overlap_total
    ops.sort(key=lambda o: (not o["exposed"], -o["wire_bytes"]))
    return {
        "by_kind": by_kind,
        "exposed_bytes": exposed_total,
        "overlappable_bytes": overlap_total,
        "exposed_fraction": exposed_total / total if total else 0.0,
        "top_exposed": [o for o in ops if o["exposed"]][:10],
        "n_collectives": len(ops),
    }


def fp32_param_bytes(hlo):
    """Sum of f32 ENTRY-parameter bytes per chip (masters + optimizer
    moments + small replicated leaves). Proves the master-weight discipline:
    sharded fp32 state is ~3 x 4 x P / N bytes, nowhere near the 12 x P a
    replicated layout would show."""
    total = 0.0
    in_entry = False
    for line in hlo.splitlines():
        s = line.strip()
        if s.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            m = re.match(r"%?[\w.\-]+\s*=\s*f32\[([\d,]*)\][^ ]*\s+parameter\(",
                         s)
            if m:
                total += _nbytes("f32", m.group(1))
    return total


def compile_with_partitioned_hlo(lowered):
    """Compile a jax ``Lowered``, also capturing the post-SPMD-partitioning
    / pre-backend-pipeline HLO snapshot via XLA's pass-dump machinery
    (per-compile compiler options — no env fiddling, no global flags).

    Returns ``(compiled, partitioned_hlo_text)``.
    """
    import jax

    def _reset_cache():
        # the cache object is a lazily-initialized global: flipping the dir
        # config alone does not evict an already-initialized instance
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass

    d = tempfile.mkdtemp(prefix="collective_audit_")
    # a persistent-compile-cache HIT skips the pass pipeline entirely — no
    # dump gets written — so the cache must be hard-off for this one compile
    # (observed: the second audit of an identical program returned no
    # snapshot; compiler_options are NOT part of the cache key).
    cache_dir_prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_cache()
        compiled = lowered.compile(compiler_options={
            "xla_dump_to": d,
            "xla_dump_hlo_pass_re": "spmd-partition.*",
        })
        files = glob.glob(os.path.join(d, "*after_spmd-partitioning*"))
        if not files:
            raise RuntimeError(
                "XLA dumped no after_spmd-partitioning snapshot (flag "
                "unsupported by this jaxlib?); cannot audit wire dtypes")
        # the audited step is by far the largest module in the dump dir
        path = max(files, key=os.path.getsize)
        with open(path) as f:
            text = f.read()
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir_prev)
        _reset_cache()  # re-initialize with the restored dir on next use
        shutil.rmtree(d, ignore_errors=True)
    return compiled, text


def audit_lowered(lowered, n_devices, loop_trip_count=1,
                  sanitizer_config=None):
    """Compile + parse: the full wire report for one lowered step program,
    including the exposed-vs-overlappable schedule split. Pass
    ``sanitizer_config`` (a dict of ``sanitizer.DEFAULTS`` overrides — at
    minimum ``{"compute_dtype": ...}``) to also run the static program
    sanitizer over the same snapshot and attach its report as a
    ``sanitizer`` section."""
    compiled, hlo = compile_with_partitioned_hlo(lowered)
    stats = parse_collectives_by_dtype(hlo, n_devices, loop_trip_count)
    schedule = audit_schedule(hlo, n_devices, loop_trip_count)
    sanitizer = None
    if sanitizer_config is not None:
        from .sanitizer import sanitize_hlo

        sanitizer = sanitize_hlo(hlo, sanitizer_config, n_devices,
                                 loop_trip_count)
    mem = compiled.memory_analysis()
    body_names = stats.pop("_loop_body_computations")
    total = sum(s["wire_bytes"] for s in stats.values())
    by_dtype = {}
    for s in stats.values():
        for dt, b in s["by_dtype"].items():
            by_dtype[dt] = by_dtype.get(dt, 0.0) + b
    report = {
        "collectives": stats,
        "schedule": schedule,
        "total_wire_bytes": total,
        "total_by_dtype": by_dtype,
        "fp32_param_bytes_per_chip": fp32_param_bytes(hlo),
        "loop_body_computations": body_names,
        "memory_per_chip": {
            "temp": mem.temp_size_in_bytes,
            "arguments": mem.argument_size_in_bytes,
            "output": mem.output_size_in_bytes,
            "alias": mem.alias_size_in_bytes,
        },
        "hlo_bytes": len(hlo),
    }
    if sanitizer is not None:
        report["sanitizer"] = sanitizer
    return report


def check_budgets(report, budget, n_params=None, n_devices=None):
    """Compare a report against one budget entry (a dict from
    ``tools/collective_budgets.json``). Returns human-readable violation
    strings (empty = pass)."""
    v = []
    ag = report["collectives"]["all-gather"]["wire_bytes"]
    if "all_gather_gb_max" in budget and \
            ag > budget["all_gather_gb_max"] * 1e9:
        v.append(f"all-gather wire {ag / 1e9:.2f} GB/chip/step exceeds "
                 f"budget {budget['all_gather_gb_max']} GB")
    if "fp32_all_gather_gb_max" in budget:
        f32 = report["collectives"]["all-gather"]["by_dtype"].get("f32", 0.0)
        if f32 > budget["fp32_all_gather_gb_max"] * 1e9:
            v.append(f"fp32 all-gather wire {f32 / 1e9:.2f} GB/chip/step "
                     f"exceeds budget {budget['fp32_all_gather_gb_max']} GB "
                     f"(fp32 master gathers reintroduced?)")
    if "total_wire_gb_max" in budget and \
            report["total_wire_bytes"] > budget["total_wire_gb_max"] * 1e9:
        v.append(f"total wire {report['total_wire_bytes'] / 1e9:.2f} "
                 f"GB/chip/step exceeds budget {budget['total_wire_gb_max']} "
                 f"GB")
    sched = report.get("schedule")
    if sched is not None:
        if "exposed_gb_max" in budget and \
                sched["exposed_bytes"] > budget["exposed_gb_max"] * 1e9:
            v.append(f"exposed collective wire "
                     f"{sched['exposed_bytes'] / 1e9:.2f} GB/chip/step "
                     f"exceeds budget {budget['exposed_gb_max']} GB (an "
                     f"overlap regression: bytes that used to hide behind "
                     f"compute now sit on the critical path)")
        if "exposed_fraction_max" in budget and \
                sched["exposed_fraction"] > budget["exposed_fraction_max"]:
            v.append(f"exposed fraction {sched['exposed_fraction']:.3f} of "
                     f"collective wire exceeds budget "
                     f"{budget['exposed_fraction_max']} (schedule audit)")
    if "sanitizer" in budget and report.get("sanitizer") is not None:
        from .sanitizer import check_sanitizer_budgets

        v.extend(check_sanitizer_budgets(report["sanitizer"],
                                         budget["sanitizer"]))
    if budget.get("masters_sharded_fp32") and n_params and n_devices:
        # sharded fp32 state (params + adam moments) ~= 3 x 4 x P / N;
        # 10% + 64 MB slack covers replicated small leaves
        bound = 3 * 4 * n_params / n_devices * 1.10 + 64e6
        got = report["fp32_param_bytes_per_chip"]
        if got > bound:
            v.append(f"fp32 argument bytes/chip {got / 1e9:.3f} GB exceed "
                     f"the sharded-master bound {bound / 1e9:.3f} GB — "
                     f"masters look replicated or upcast")
    return v
