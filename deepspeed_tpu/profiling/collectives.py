"""HLO collective-bytes accounting (by kind AND payload dtype).

The compiler is the source of truth for wire traffic, the same way it is for
flops (``flops_profiler.py``): every collective in the compiled step is
parsed out of the HLO with its payload dtype, and ring-algorithm wire costs
are attributed per chip per step. Used by:

- ``tools/collective_audit.py`` — the CI gate that keeps fp32 master
  gathers from silently reappearing on the ZeRO-3 hot path;
- ``FlopsProfiler`` (``collectives=True``) — live wire-bytes alongside
  flops;
- ``DeepSpeedEngine.collective_wire_stats`` — monitor events for training
  runs (``comms_logger.enabled``).

Why the post-partitioning snapshot: the CPU backend's float-normalization
pass legalizes bf16 collectives to f32 + converts (CPU has no native bf16),
so the backend-optimized HLO shows fp32 gathers regardless of what the
program pinned. The snapshot taken right after the SPMD partitioner — via
XLA's pass-dump machinery, per-compile — is the platform-independent SPMD
program a TPU receives, with the partitioner's committed wire dtypes.
(int8 payloads survive even the CPU pipeline: integer collectives are not
float-normalized — a useful cross-check.)
"""

import glob
import os
import re
import shutil
import tempfile

DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "s64": 8, "u64": 8, "s8": 1, "u8": 1, "pred": 1, "s16": 2,
               "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 0.5, "u4": 0.5}

_RESULT_RE = re.compile(r"=\s+(?:\()?(\w+)\[([\d,]*)\]")
_TUPLE_SHAPES_RE = re.compile(r"(\w+)\[([\d,]*)\]")
KINDS = ("all-gather", "reduce-scatter", "all-reduce", "all-to-all",
         "collective-permute")


def _nbytes(dtype, dims):
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * DTYPE_BYTES.get(dtype, 4)


def _result_shape(line, is_start=False):
    """(dtype, dims) of the op's RESULT. Async ``-start`` ops return a tuple
    ``(operand, ..., output)`` — the output (last element) is the
    gathered/reduced result; counting the first would skew all-gather ~N x."""
    if is_start:
        head = line.split("-start(")[0]
        shapes = _TUPLE_SHAPES_RE.findall(head)
        return shapes[-1] if shapes else None
    m = _RESULT_RE.search(line)
    return m.groups() if m else None


_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _group_size(line, default_n):
    """Ring size of THIS op: the replica-group size from the op's
    ``replica_groups`` attribute, not the global device count. On a
    multi-axis mesh a ZeRO reduce-scatter spans only the ``data`` group —
    charging it the full device product would overreport by the non-data
    mesh factor. Explicit list form ``{{0,1,..},..}`` and iota form
    ``[groups,size]<=[N]`` are both parsed; absent/empty groups mean
    all devices."""
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2))
    return default_n


def parse_collectives_by_dtype(hlo, n_devices, loop_trip_count=1):
    """Per-chip wire bytes for each collective kind, split by payload dtype.

    Wire accounting (ring algorithms, per chip, with G = the op's OWN
    replica-group size, falling back to ``n_devices`` when the op carries no
    groups): all-gather receives (G-1)/G of the full result; reduce-scatter
    sends (G-1)/G of the full input (= result x G); all-reduce is RS+AG =
    2 x (G-1)/G x full; all-to-all moves (G-1)/G of its payload;
    collective-permute moves its payload once.

    Ops inside a ``while`` body appear ONCE in the text but run once per
    iteration — multiplied by ``loop_trip_count`` (= n_layers for the layer
    scan; the same static-text trap that broke the r4 autotuner cost model).
    Documented approximation: every while in the audited programs is a layer
    scan (the audit runs with gradient accumulation 1).
    """
    body_names = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    stats = {k: {"count": 0, "wire_bytes": 0.0, "by_dtype": {},
                 "by_computation": {}} for k in KINDS}
    comp = "<entry>"
    for line in hlo.splitlines():
        s = line.strip()
        # computation headers, both HLO text styles: the full signature form
        # `%name (p: ...) -> type {` and the pass-dump compact form `name {`
        if s.endswith("{") and "=" not in s and not s.startswith("ROOT"):
            m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*[({]", s)
            if m and m.group(1) not in ("if", "while", "true", "false"):
                comp = m.group(1)
            continue
        for kind in stats:
            if f" {kind}(" in s or f" {kind}-start(" in s:
                shape = _result_shape(s, is_start=f" {kind}-start(" in s)
                if shape is None:
                    break
                dtype, dims = shape
                b = _nbytes(dtype, dims)
                g = _group_size(s, n_devices)
                frac = (g - 1) / g if g > 1 else 1.0
                if kind == "all-gather":
                    wire = b * frac
                elif kind == "reduce-scatter":
                    wire = b * g * frac
                elif kind == "all-reduce":
                    wire = 2 * b * frac
                elif kind == "all-to-all":
                    wire = b * frac
                else:  # collective-permute
                    wire = b
                if comp in body_names:
                    wire *= loop_trip_count
                st = stats[kind]
                st["count"] += 1
                st["wire_bytes"] += wire
                st["by_dtype"][dtype] = st["by_dtype"].get(dtype, 0.0) + wire
                st["by_computation"][comp] = \
                    st["by_computation"].get(comp, 0) + 1
                break
    stats["_loop_body_computations"] = sorted(body_names)
    return stats


def fp32_param_bytes(hlo):
    """Sum of f32 ENTRY-parameter bytes per chip (masters + optimizer
    moments + small replicated leaves). Proves the master-weight discipline:
    sharded fp32 state is ~3 x 4 x P / N bytes, nowhere near the 12 x P a
    replicated layout would show."""
    total = 0.0
    in_entry = False
    for line in hlo.splitlines():
        s = line.strip()
        if s.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            m = re.match(r"%?[\w.\-]+\s*=\s*f32\[([\d,]*)\][^ ]*\s+parameter\(",
                         s)
            if m:
                total += _nbytes("f32", m.group(1))
    return total


def compile_with_partitioned_hlo(lowered):
    """Compile a jax ``Lowered``, also capturing the post-SPMD-partitioning
    / pre-backend-pipeline HLO snapshot via XLA's pass-dump machinery
    (per-compile compiler options — no env fiddling, no global flags).

    Returns ``(compiled, partitioned_hlo_text)``.
    """
    import jax

    def _reset_cache():
        # the cache object is a lazily-initialized global: flipping the dir
        # config alone does not evict an already-initialized instance
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass

    d = tempfile.mkdtemp(prefix="collective_audit_")
    # a persistent-compile-cache HIT skips the pass pipeline entirely — no
    # dump gets written — so the cache must be hard-off for this one compile
    # (observed: the second audit of an identical program returned no
    # snapshot; compiler_options are NOT part of the cache key).
    cache_dir_prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_cache()
        compiled = lowered.compile(compiler_options={
            "xla_dump_to": d,
            "xla_dump_hlo_pass_re": "spmd-partition.*",
        })
        files = glob.glob(os.path.join(d, "*after_spmd-partitioning*"))
        if not files:
            raise RuntimeError(
                "XLA dumped no after_spmd-partitioning snapshot (flag "
                "unsupported by this jaxlib?); cannot audit wire dtypes")
        # the audited step is by far the largest module in the dump dir
        path = max(files, key=os.path.getsize)
        with open(path) as f:
            text = f.read()
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir_prev)
        _reset_cache()  # re-initialize with the restored dir on next use
        shutil.rmtree(d, ignore_errors=True)
    return compiled, text


def audit_lowered(lowered, n_devices, loop_trip_count=1):
    """Compile + parse: the full wire report for one lowered step program."""
    compiled, hlo = compile_with_partitioned_hlo(lowered)
    stats = parse_collectives_by_dtype(hlo, n_devices, loop_trip_count)
    mem = compiled.memory_analysis()
    body_names = stats.pop("_loop_body_computations")
    total = sum(s["wire_bytes"] for s in stats.values())
    by_dtype = {}
    for s in stats.values():
        for dt, b in s["by_dtype"].items():
            by_dtype[dt] = by_dtype.get(dt, 0.0) + b
    return {
        "collectives": stats,
        "total_wire_bytes": total,
        "total_by_dtype": by_dtype,
        "fp32_param_bytes_per_chip": fp32_param_bytes(hlo),
        "loop_body_computations": body_names,
        "memory_per_chip": {
            "temp": mem.temp_size_in_bytes,
            "arguments": mem.argument_size_in_bytes,
            "output": mem.output_size_in_bytes,
            "alias": mem.alias_size_in_bytes,
        },
        "hlo_bytes": len(hlo),
    }


def check_budgets(report, budget, n_params=None, n_devices=None):
    """Compare a report against one budget entry (a dict from
    ``tools/collective_budgets.json``). Returns human-readable violation
    strings (empty = pass)."""
    v = []
    ag = report["collectives"]["all-gather"]["wire_bytes"]
    if "all_gather_gb_max" in budget and \
            ag > budget["all_gather_gb_max"] * 1e9:
        v.append(f"all-gather wire {ag / 1e9:.2f} GB/chip/step exceeds "
                 f"budget {budget['all_gather_gb_max']} GB")
    if "fp32_all_gather_gb_max" in budget:
        f32 = report["collectives"]["all-gather"]["by_dtype"].get("f32", 0.0)
        if f32 > budget["fp32_all_gather_gb_max"] * 1e9:
            v.append(f"fp32 all-gather wire {f32 / 1e9:.2f} GB/chip/step "
                     f"exceeds budget {budget['fp32_all_gather_gb_max']} GB "
                     f"(fp32 master gathers reintroduced?)")
    if "total_wire_gb_max" in budget and \
            report["total_wire_bytes"] > budget["total_wire_gb_max"] * 1e9:
        v.append(f"total wire {report['total_wire_bytes'] / 1e9:.2f} "
                 f"GB/chip/step exceeds budget {budget['total_wire_gb_max']} "
                 f"GB")
    if budget.get("masters_sharded_fp32") and n_params and n_devices:
        # sharded fp32 state (params + adam moments) ~= 3 x 4 x P / N;
        # 10% + 64 MB slack covers replicated small leaves
        bound = 3 * 4 * n_params / n_devices * 1.10 + 64e6
        got = report["fp32_param_bytes_per_chip"]
        if got > bound:
            v.append(f"fp32 argument bytes/chip {got / 1e9:.3f} GB exceed "
                     f"the sharded-master bound {bound / 1e9:.3f} GB — "
                     f"masters look replicated or upcast")
    return v
