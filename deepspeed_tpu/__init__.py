"""deepspeed_tpu — a TPU-native distributed training & inference framework.

Capability surface of DeepSpeed 0.9.1 (reference ``deepspeed/__init__.py``), designed
TPU-first: sharding specs + XLA collectives over a named ``jax.sharding.Mesh`` instead
of NCCL hook machinery, Pallas kernels instead of CUDA extensions.

Public API (mirrors reference ``deepspeed/__init__.py:54,:251``):
    initialize()       -> (engine, optimizer, dataloader, lr_scheduler)
    init_inference()   -> InferenceEngine
    init_distributed() -> multi-host rendezvous
"""

__version__ = "0.1.0"
__git_branch__ = "main"

# jax API drift shim (jax.shard_map on 0.4.x jaxlibs) — must run before any
# submodule builds a shard_map program
from .utils import jax_compat as _jax_compat

_jax_compat.install()

from . import comm  # noqa: F401
from . import pipe  # noqa: F401
from . import zero  # noqa: F401
from .accelerator import get_accelerator, set_accelerator  # noqa: F401
from .config import DeepSpeedConfig, load_config  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401
from .utils import OnDevice  # noqa: F401  (reference deepspeed.OnDevice)


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mesh=None, dist_init_required=None,
               collate_fn=None, config=None, config_params=None):
    """Build a training engine (reference ``deepspeed/__init__.py:54``).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    ``mesh`` replaces the reference's ``mpu`` argument: pass a prebuilt
    ``jax.sharding.Mesh`` or let the config's ``mesh`` section build one.
    """
    from .runtime.engine import DeepSpeedEngine

    config = config if config is not None else config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("deepspeed_tpu.initialize requires a config (dict or JSON path)")

    if dist_init_required or dist_init_required is None:
        init_distributed()

    # engine class choice (reference deepspeed/__init__.py:141-181): the hybrid
    # (RLHF) engine when configured, else the plain training engine (pipeline
    # scheduling lives inside the engine here, not in a subclass).
    engine_cls = DeepSpeedEngine
    config = load_config(config)  # parse once; the engine accepts the instance
    if config.hybrid_engine.enabled:
        from .runtime.hybrid_engine import DeepSpeedHybridEngine

        engine_cls = DeepSpeedHybridEngine

    engine = engine_cls(
        model=model,
        optimizer=optimizer,
        model_parameters=model_parameters,
        training_data=training_data,
        lr_scheduler=lr_scheduler,
        mesh=mesh,
        collate_fn=collate_fn,
        config=config,
    )
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Build an inference engine (reference ``deepspeed/__init__.py:251``).

    ``model`` may be a live zoo model OR a path to a HuggingFace checkpoint
    directory (the reference's ``init_inference(model, checkpoint=...)`` +
    module_inject flow): the checkpoint is mapped into the zoo's pytree and
    served with auto-TP placement (``module_inject/hf.py``).
    """
    from .inference.engine import InferenceEngine
    from .inference.config import DeepSpeedInferenceConfig

    if isinstance(config, DeepSpeedInferenceConfig):
        ds_config = config
    else:
        merged = dict(config or {})
        merged.update(kwargs)
        ds_config = DeepSpeedInferenceConfig.from_dict(merged)

    if isinstance(model, str):
        import jax

        from .module_inject import hf_model_from_pretrained
        from .models.layers import split_params_axes

        model, values = hf_model_from_pretrained(model)
        axes = split_params_axes(
            jax.eval_shape(model.init, jax.random.PRNGKey(0)))[1]
        return InferenceEngine(model, ds_config, model_parameters=(values, axes))
    return InferenceEngine(model, ds_config)


def add_config_arguments(parser):
    """Reference ``deepspeed/__init__.py:228``: add --deepspeed/--deepspeed_config."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for config scripts)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU JSON config")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS
