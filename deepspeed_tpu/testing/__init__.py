from .fault_injection import (
    ChaosSchedule,
    FaultInjector,
    InjectedFault,
    ReplicaChaosSchedule,
    truncate_file,
    sigterm_data_iter,
)

__all__ = [
    "ChaosSchedule", "FaultInjector", "InjectedFault",
    "ReplicaChaosSchedule", "truncate_file", "sigterm_data_iter",
]
