from .fault_injection import (
    FaultInjector,
    InjectedFault,
    truncate_file,
    sigterm_data_iter,
)

__all__ = [
    "FaultInjector", "InjectedFault", "truncate_file", "sigterm_data_iter",
]
