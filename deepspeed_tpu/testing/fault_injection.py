"""Deterministic fault injection for checkpoint durability tests.

Hooks into the fault-point seam in ``checkpoint/atomic.py`` (every durable
write funnels through ``write_bytes``/``write_npz``/``write_json``, which fire
``fault_point(event, path)`` before and after touching the disk, plus
``replace``/``latest`` events at the two commit points). The injector can:

- **fail the Nth write** matching a path pattern (transient or permanent) —
  simulates ENOSPC / network-fs flakes / a crash mid-save;
- **truncate a file right after it is written** — simulates a torn write that
  made it to disk (fired at the ``wrote`` event, before fsync);
- **raise only in a background thread** — proves async writer failures
  surface at ``commit()`` instead of vanishing;
- **deliver SIGTERM at a chosen training step** via ``sigterm_data_iter`` —
  drives the ElasticAgent preemption path end-to-end.

All counters are deterministic: the Nth matching event is the Nth call, no
randomness. Usage::

    with FaultInjector() as fi:
        fi.fail_write(match="arrays.npz")            # every write fails
        fi.fail_write(match="meta.json", nth=2, times=1)  # only the 2nd
        ...

The harness is test-only but ships in the package so downstream users can
prove their own recovery paths.
"""

import os
import threading

from ..checkpoint import atomic


class InjectedFault(OSError):
    """Default exception raised by injected write failures. An ``OSError``
    subclass so it exercises the real retry/backoff path."""


def truncate_file(path, keep_bytes=None, drop_bytes=None):
    """Deterministically truncate ``path``: keep the first ``keep_bytes``, or
    drop the last ``drop_bytes`` (default: drop half)."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = max(0, size - (drop_bytes if drop_bytes is not None
                                    else size // 2))
    with open(path, "rb+") as f:
        f.truncate(keep_bytes)
    return keep_bytes


def sigterm_data_iter(data_iter, at_step):
    """Wrap a training data iterator; the ``at_step``-th ``next()`` (1-based)
    delivers SIGTERM to this process before yielding — the preemption arrives
    exactly at a chosen step."""
    import signal

    step = 0
    for batch in data_iter:
        step += 1
        if step == at_step:
            os.kill(os.getpid(), signal.SIGTERM)
        yield batch


class ChaosSchedule:
    """Seeded kill/resize schedule for preemption chaos runs.

    Draws ``n_kills`` strictly-increasing SIGTERM steps in
    ``[min_gap, total_steps - 1]`` (each at least ``min_gap`` apart, so every
    segment makes progress) and assigns each restart the next mesh from the
    ``meshes`` cycle (``8 -> 4 -> 8`` style). Deterministic: the same seed
    always produces the same trajectory, which is what lets chaos tests
    assert exact step continuity rather than "it survived".

    ``events`` is ``[(kill_step, resume_mesh), ...]``; ``mesh_at(segment)``
    names the mesh segment ``i`` runs on (segment 0 = the initial mesh =
    ``meshes[0]``, the segment after kill ``i`` runs on ``events[i][1]``).
    """

    def __init__(self, seed, total_steps, n_kills, meshes=None, min_gap=2):
        import numpy as np

        if total_steps < (n_kills + 1) * min_gap:
            raise ValueError(
                f"total_steps={total_steps} too small for {n_kills} kills "
                f"with min_gap={min_gap}")
        self.seed = seed
        self.total_steps = total_steps
        self.meshes = list(meshes) if meshes else [{"data": 8}]
        rng = np.random.RandomState(seed)
        steps, floor = [], min_gap
        for i in range(n_kills):
            # leave room for the remaining kills' gaps
            ceil = total_steps - 1 - (n_kills - 1 - i) * min_gap
            if floor > ceil:
                raise ValueError("schedule does not fit; raise total_steps")
            s = int(rng.randint(floor, ceil + 1))
            steps.append(s)
            floor = s + min_gap
        self.kill_steps = steps
        self.events = [(s, self.meshes[(i + 1) % len(self.meshes)])
                       for i, s in enumerate(steps)]

    def mesh_at(self, segment):
        return self.meshes[segment % len(self.meshes)]

    def __iter__(self):
        return iter(self.events)

    def __len__(self):
        return len(self.events)


class ReplicaChaosSchedule:
    """Seeded replica-level fault schedule for serving-fleet chaos runs —
    the serving-tier sibling of :class:`ChaosSchedule`.

    Draws ``n_kills + n_stalls`` strictly-increasing virtual-clock instants
    in ``[min_gap, horizon - min_gap]`` (each at least ``min_gap`` apart, so
    every segment makes progress), assigns each a target replica (kills
    draw WITHOUT replacement — a replica dies at most once; stalls draw
    with replacement over all replicas) and shuffles which instants are
    kills vs stalls. Deterministic: the same seed always produces the same
    schedule, which is what lets fleet chaos tests assert exact recovery
    (zero lost committed tokens, identical shed sets) rather than "it
    survived".

    ``events`` is ``[(time, kind, replica, duration), ...]`` sorted by
    time, directly consumable by ``Router.apply_chaos``.
    """

    def __init__(self, seed, horizon, n_replicas, n_kills, n_stalls=0,
                 stall_duration=0.25, min_gap=0.05):
        import numpy as np

        n_events = n_kills + n_stalls
        if n_kills > n_replicas:
            raise ValueError(
                f"n_kills={n_kills} exceeds n_replicas={n_replicas} "
                "(kills draw without replacement)")
        if horizon < (n_events + 1) * min_gap:
            raise ValueError(
                f"horizon={horizon} too small for {n_events} events "
                f"with min_gap={min_gap}")
        self.seed = seed
        self.horizon = float(horizon)
        self.n_replicas = int(n_replicas)
        rng = np.random.RandomState(seed)
        times, floor = [], min_gap
        for i in range(n_events):
            # leave room for the remaining events' gaps (the ChaosSchedule
            # draw, on a continuous clock)
            ceil = horizon - min_gap - (n_events - 1 - i) * min_gap
            if floor > ceil:
                raise ValueError("schedule does not fit; raise horizon")
            t = float(rng.uniform(floor, ceil))
            times.append(t)
            floor = t + min_gap
        kinds = ["kill"] * n_kills + ["stall"] * n_stalls
        kinds = [kinds[i] for i in rng.permutation(n_events)] \
            if n_events else []
        kill_targets = list(rng.permutation(n_replicas)[:n_kills])
        stall_targets = [int(rng.randint(0, n_replicas))
                         for _ in range(n_stalls)]
        events = []
        for t, kind in zip(times, kinds):
            if kind == "kill":
                replica = int(kill_targets.pop(0))
                events.append((t, "kill", replica, 0.0))
            else:
                events.append((t, "stall", stall_targets.pop(0),
                               float(stall_duration)))
        self.events = events
        self.kill_times = [e[0] for e in events if e[1] == "kill"]

    def __iter__(self):
        return iter(self.events)

    def __len__(self):
        return len(self.events)


class _Fault:
    def __init__(self, event, match, nth, times, action, only_background):
        self.event = event
        self.match = match
        self.nth = nth
        self.times = times  # None = every match from nth on
        self.action = action
        self.only_background = only_background
        self.seen = 0
        self.fired = 0

    def maybe_fire(self, event, path):
        if event != self.event:
            return
        if self.match and self.match not in path:
            return
        if (self.only_background
                and threading.current_thread() is threading.main_thread()):
            return
        self.seen += 1
        if self.seen < self.nth:
            return
        if self.times is not None and self.fired >= self.times:
            return
        self.fired += 1
        self.action(path)


class FaultInjector:
    """Context manager registering deterministic faults at the atomic-write
    seam. Faults stack; each keeps its own occurrence counter."""

    def __init__(self):
        self._faults = []
        self._hook_installed = False

    # -- registration -------------------------------------------------------
    def _add(self, event, match, nth, times, action, only_background=False):
        fault = _Fault(event, match, nth, times, action, only_background)
        self._faults.append(fault)
        return fault

    def fail_write(self, match="", nth=1, times=None, exc=None,
                   only_background=False):
        """Raise before the Nth matching data-file write (and on every later
        match unless ``times`` bounds it). ``times=1`` models a transient
        error the retry policy should absorb."""
        err = exc or InjectedFault(f"injected write failure (match={match!r})")

        def action(path):
            raise err

        return self._add("write", match, nth, times, action, only_background)

    def truncate_write(self, match="", nth=1, times=1, keep_bytes=0,
                       then_fail=True):
        """Truncate the file right after the Nth matching write lands (the
        ``wrote`` event — on disk, not yet fsynced). With ``then_fail`` (the
        default) the write call also raises: the classic torn-write crash —
        half a file on disk and the save dead. ``then_fail=False`` leaves the
        truncation silent, which the COMMITTED marker will then faithfully
        checksum — use :func:`truncate_file` on a *committed* checkpoint to
        model post-commit corruption instead."""

        def action(path):
            truncate_file(path, keep_bytes=keep_bytes)
            if then_fail:
                raise InjectedFault(f"injected torn write on {path}")

        return self._add("wrote", match, nth, times, action)

    def fail_replace(self, match="", nth=1, times=None, exc=None):
        """Raise at the tag-dir commit rename — the save died after staging
        everything but before publication."""
        err = exc or InjectedFault("injected failure at tag publish")

        def action(path):
            raise err

        return self._add("replace", match, nth, times, action)

    def fail_latest(self, match="", nth=1, times=None, exc=None):
        """Raise at the ``latest``-pointer swap — the tag committed but the
        pointer never moved (resume must still find the tag)."""
        err = exc or InjectedFault("injected failure at latest swap")

        def action(path):
            raise err

        return self._add("latest", match, nth, times, action)

    def fail_async_write(self, match="", nth=1, times=None, exc=None):
        """Like :meth:`fail_write` but only fires off the main thread —
        targets the async engines' background writer specifically."""
        return self.fail_write(match=match, nth=nth, times=times, exc=exc,
                               only_background=True)

    # -- bookkeeping --------------------------------------------------------
    @property
    def total_fired(self):
        return sum(f.fired for f in self._faults)

    def writes_seen(self):
        """Matching-event counts per registered fault (harness self-tests)."""
        return [f.seen for f in self._faults]

    # -- hook lifecycle -----------------------------------------------------
    def _hook(self, event, path):
        for fault in self._faults:
            fault.maybe_fire(event, path)

    def __enter__(self):
        atomic.register_fault_hook(self._hook)
        self._hook_installed = True
        return self

    def __exit__(self, *exc_info):
        if self._hook_installed:
            atomic.unregister_fault_hook(self._hook)
            self._hook_installed = False
        return False
