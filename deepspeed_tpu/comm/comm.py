"""Communication facade.

TPU-native equivalent of the reference's ``deepspeed/comm`` package
(``comm/comm.py:14`` — a torch.distributed-compatible op surface dispatching to a
``TorchBackend`` over NCCL). Here the "backend" is XLA: collectives are ``jax.lax``
primitives traced inside ``jit``/``shard_map`` over a named-axis ``Mesh``; process
groups become mesh axis names. ICI carries intra-slice traffic, DCN inter-slice —
placement follows mesh axis order (see ``parallel/topology.py``).

Two tiers:
- **In-program collectives** (``all_reduce``/``all_gather``/``reduce_scatter``/
  ``all_to_all``/``ppermute``): called inside ``shard_map``; compiled by XLA.
- **Host-control ops** (``barrier``/``broadcast_obj``): eager, via
  ``jax.experimental.multihost_utils`` — the reference uses NCCL broadcast for these.

Every op is wrapped with the reference's ``timed_op``-style comms logger
(``comm/comm.py:104`` + ``utils/comms_logging.py``): since XLA ops are traced once and
replayed, we record *trace-time* op descriptors (name, payload bytes, axis) — the
per-call latency attribution lives in the profiler, not here.
"""

import functools
import os
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger, log_dist


class ReduceOp:
    """Reference: ``comm/comm.py:33``."""

    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


# ---------------------------------------------------------------------------------
# Comms logger (reference utils/comms_logging.py + comm/comm.py:104 timed_op)
# ---------------------------------------------------------------------------------
class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops = []
        self.records = {}  # op_name -> list of (bytes, axis)

    def configure(self, config):
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)

    def should_log(self, op_name):
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def record(self, op_name, nbytes, axis):
        if not self.should_log(op_name):
            return
        self.records.setdefault(op_name, []).append((int(nbytes), axis))
        if self.verbose:
            log_dist(f"comm op: {op_name} | bytes: {nbytes} | axis: {axis}", ranks=[0])

    def log_summary(self):
        """Reference ``comm/comm.py:409`` log_summary."""
        lines = ["Comms summary (trace-time):"]
        for op, recs in sorted(self.records.items()):
            total = sum(b for b, _ in recs)
            lines.append(f"  {op}: count={len(recs)} total_bytes={total}")
        log_dist("\n".join(lines), ranks=[0])
        return self.records


comms_logger = CommsLogger()


def _nbytes(x):
    try:
        return x.size * jnp.dtype(x.dtype).itemsize
    except Exception:
        return 0


def _logged(op_name):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(tensor, *args, **kwargs):
            axis = kwargs.get("axis_name") or (args[0] if args else None)
            comms_logger.record(op_name, _nbytes(tensor), axis)
            return fn(tensor, *args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------------
# In-program collectives (use inside shard_map over the framework Mesh)
# ---------------------------------------------------------------------------------
@_logged("all_reduce")
def all_reduce(tensor, axis_name, op=ReduceOp.SUM):
    """Reference ``comm/comm.py:214`` all_reduce -> ``lax.psum``/pmean/pmax/..."""
    if op == ReduceOp.SUM:
        return jax.lax.psum(tensor, axis_name)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(tensor, axis_name)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(tensor, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(tensor, axis_name)
    if op == ReduceOp.PROD:
        # exact product: gather then multiply (no log-space sign/zero pitfalls)
        gathered = jax.lax.all_gather(tensor, axis_name, axis=0)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unsupported reduce op {op}")


@_logged("all_gather")
def all_gather(tensor, axis_name, axis=0, tiled=True):
    """Reference ``all_gather_into_tensor`` (``comm/comm.py:298``): concatenate along
    ``axis`` across the mesh axis."""
    return jax.lax.all_gather(tensor, axis_name, axis=axis, tiled=tiled)


@_logged("reduce_scatter")
def reduce_scatter(tensor, axis_name, scatter_dimension=0, tiled=True):
    """Reference ``reduce_scatter_tensor`` (``comm/comm.py:257``) /
    ``reduce_scatter_coalesced`` (``runtime/comm/coalesced_collectives.py:29``) ->
    ``lax.psum_scatter``. Coalescing is XLA's job (it fuses adjacent collectives)."""
    return jax.lax.psum_scatter(tensor, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


@_logged("all_to_all")
def all_to_all(tensor, axis_name, split_axis=0, concat_axis=0, tiled=True):
    """Reference ``all_to_all_single`` (``comm/comm.py:341``) and the MoE ``_AllToAll``
    autograd op (``moe/sharded_moe.py:90``) -> ``lax.all_to_all``."""
    return jax.lax.all_to_all(
        tensor, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


@_logged("ppermute")
def ppermute(tensor, axis_name, perm):
    """Point-to-point ring/neighbor exchange — replaces the reference's pipeline
    ``send``/``recv`` (``runtime/pipe/p2p.py:50,:71``); perm is [(src, dst), ...]."""
    return jax.lax.ppermute(tensor, axis_name, perm)


def send_recv_next(tensor, axis_name, axis_size):
    """Shift tensors one step toward the next pipeline stage (wrapping)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return ppermute(tensor, axis_name, perm)


def send_recv_prev(tensor, axis_name, axis_size):
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    return ppermute(tensor, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


@_logged("broadcast")
def broadcast_in_program(tensor, axis_name, src=0):
    """Broadcast from ``src`` along a mesh axis inside a program: implemented as a
    select + psum (XLA lowers this to a broadcast-like collective)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return jax.lax.psum(masked, axis_name)


# ---------------------------------------------------------------------------------
# Host-control plane (eager, multi-host)
# ---------------------------------------------------------------------------------
def _rank_from_hostlist(hosts_csv):
    """Rank = this host's index in the pdsh broadcast host list. Short and
    fully-qualified spellings match in either direction (a pdsh -w list of
    FQDNs with a short gethostname(), or vice versa)."""
    import socket

    hosts = [h.strip() for h in hosts_csv.split(",") if h.strip()]
    fqdn = socket.gethostname()
    short = fqdn.split(".")[0]
    matches = [i for i, h in enumerate(hosts)
               if h == fqdn or h == short or h.split(".")[0] in (fqdn, short)]
    if len(matches) > 1:
        # e.g. DS_TPU_HOSTS="a.dc1,a.dc2" with gethostname()=="a": two hosts
        # would silently derive the SAME rank and hang/corrupt jax.distributed
        # init — refuse instead
        raise RuntimeError(
            f"init_distributed: hostname {fqdn} matches multiple entries of "
            f"DS_TPU_HOSTS ({hosts_csv}) at indices {matches} — use "
            f"fully-qualified names in the hostfile to disambiguate")
    if matches:
        return matches[0]
    raise RuntimeError(
        f"init_distributed: this host ({fqdn}) is not in DS_TPU_HOSTS "
        f"({hosts_csv}) — the pdsh transport must launch on exactly the "
        f"listed hosts")


_initialized = False


def is_initialized():
    return _initialized


def init_distributed(dist_backend=None, timeout=None, init_method=None, rank=-1, world_size=-1):
    """Reference ``comm/comm.py:526`` init_distributed.

    On TPU pods, ``jax.distributed.initialize()`` performs the rendezvous (coordinator
    address from the environment / cloud metadata, the role played by MASTER_ADDR +
    NCCL rendezvous in the reference). Single-process runs skip it.
    """
    global _initialized
    if _initialized:
        return

    def _env_first(*names, default=""):
        for n in names:
            v = os.environ.get(n, "")
            if v != "":
                return v
        return default

    # World size / rank: our launcher env first, then the scheduler's own
    # (srun exports SLURM_NTASKS/SLURM_PROCID; mpirun exports
    # OMPI_COMM_WORLD_SIZE/RANK) — the slurm/openmpi transports deliberately
    # export only the coordinator address and let the scheduler number ranks.
    # The scheduler fallback engages ONLY when a coordinator address is set:
    # a plain `python train.py` inside an `#SBATCH --ntasks=8` allocation also
    # sees SLURM_NTASKS=8, and without a coordinator it must stay a normal
    # single-process run, not hang waiting for seven peers that never arrive.
    coordinator = _env_first("DS_TPU_COORDINATOR", "MASTER_ADDR")
    num_processes = int(os.environ.get("DS_TPU_NUM_PROCESSES", "0"))
    if num_processes == 0 and coordinator:
        num_processes = int(_env_first(
            "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE", default="0"))
    if num_processes > 1:
        if not coordinator:
            raise RuntimeError(
                "init_distributed: DS_TPU_NUM_PROCESSES > 1 but no coordinator "
                "address — set DS_TPU_COORDINATOR (or MASTER_ADDR) to the host "
                "that runs process 0")
        port = os.environ.get("MASTER_PORT", "8476")
        pid_env = _env_first(
            "DS_TPU_PROCESS_ID", "RANK", "SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
            "PMI_RANK")
        if pid_env == "" and os.environ.get("DS_TPU_HOSTS"):
            # pdsh transport: the SAME command line reaches every host, so the
            # rank is derived from this host's position in the broadcast host
            # list (the reference's launch.py node_rank-from-world-info role,
            # multinode_runner.py:51 PDSHRunner)
            pid_env = str(_rank_from_hostlist(os.environ["DS_TPU_HOSTS"]))
        process_id = int(pid_env or "0")
        # The coordinator races worker restarts on pod preemption: workers
        # relaunched a beat before process 0 see connection refused. Retry the
        # handshake with backoff instead of dying (knobs: DS_TPU_INIT_RETRIES /
        # DS_TPU_INIT_BACKOFF seconds).
        from ..utils.retry import RetryPolicy, retry_call

        def _transient(exc):
            # RuntimeErrors are retried only when they look like rendezvous
            # trouble; 'already initialized' / bad-address errors must surface
            # immediately, not after a masked backoff schedule
            if isinstance(exc, (OSError, ConnectionError)):
                return True
            msg = str(exc).lower()
            return any(s in msg for s in ("timeout", "timed out", "deadline",
                                          "unavailable", "connect", "refused",
                                          "reset", "temporarily"))

        handshake_policy = RetryPolicy(
            max_attempts=int(os.environ.get("DS_TPU_INIT_RETRIES", "3")),
            base_delay=float(os.environ.get("DS_TPU_INIT_BACKOFF", "1.0")),
            max_delay=30.0,
            retry_on=(RuntimeError, OSError, ConnectionError),
            retry_if=_transient,
        )

        def _teardown_half_init(exc, attempt):
            # jax assigns global_state.client BEFORE client.connect(), so a
            # failed handshake leaves half-initialized state and the next
            # initialize() would die with 'should only be called once'
            # instead of retrying the connect — tear it down between attempts
            try:
                jax.distributed.shutdown()
            except Exception:
                # client.shutdown() itself fails on a never-connected client
                # (and then State.shutdown leaves .client set) — force-clear
                try:
                    from jax._src import distributed as _jdist

                    state = _jdist.global_state
                    for attr in ("client", "service"):
                        obj = getattr(state, attr, None)
                        if obj is not None:
                            try:
                                obj.shutdown()
                            except Exception:
                                pass
                            setattr(state, attr, None)
                except Exception:
                    pass

        retry_call(
            jax.distributed.initialize,
            coordinator_address=f"{coordinator}:{port}",
            num_processes=num_processes,
            process_id=process_id,
            policy=handshake_policy,
            on_retry=_teardown_half_init,
            describe=f"coordinator handshake ({coordinator}:{port})",
        )
        log_dist(
            f"Initialized distributed JAX: {num_processes} processes, "
            f"coordinator {coordinator}:{port}",
            ranks=[0],
        )
    _initialized = True


def get_rank():
    """Process index (reference get_rank is per-GPU rank; on TPU, per-host process)."""
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def get_local_device_count():
    return jax.local_device_count()


def get_global_device_count():
    return jax.device_count()


def barrier():
    """Reference ``comm/comm.py:457`` barrier -> multihost sync."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")


def broadcast_obj(obj, src=0):
    """Host-side object broadcast (reference ``pipe/p2p.py:100`` send_obj /
    engine broadcasts of small python objects).

    Arbitrary picklable objects: pickled to bytes, length broadcast first (fixed
    shape), then the padded payload — multihost broadcast only moves numeric arrays.
    """
    if jax.process_count() == 1:
        return obj
    import pickle

    from jax.experimental import multihost_utils

    is_source = jax.process_index() == src
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8) if is_source else np.zeros(0, np.uint8)
    length = multihost_utils.broadcast_one_to_all(
        np.asarray(payload.size, np.int32), is_source=is_source
    )
    buf = np.zeros(int(length), np.uint8)
    if is_source:
        buf[:] = payload
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    return pickle.loads(np.asarray(buf).tobytes())


def allgather_obj(obj):
    """Host-side object all-gather: every process contributes one picklable
    object, every process gets the list ordered by process index. Collective.
    Payloads are pickled, padded to the group max, and moved with two
    ``process_allgather`` calls — multihost gathers only move numeric arrays.
    """
    if jax.process_count() == 1:
        return [obj]
    import pickle

    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.asarray(payload.size, np.int64))).reshape(-1)
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[:payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(gathered[i, :int(sizes[i])].tobytes())
            for i in range(gathered.shape[0])]


def all_agree(flag):
    """Host-side consensus: True iff EVERY process passes ``flag`` truthy.
    Collective — all processes must call it. Single-process: just bool(flag).
    Used where a rank-local failure (e.g. one host's checkpoint read) must
    fail the whole group instead of letting ranks silently diverge."""
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray(bool(flag)))
    return bool(np.all(flags))


def assert_same_across_ranks(values, name="value"):
    """Cross-rank consistency guard (reference ``runtime/zero/utils.py:73``
    ``assert_ints_same_as_other_ranks`` + the ZeRO-3 ``safe_mode`` checks,
    ``partition_parameters.py:898``): every process must hold the same host-side
    values, else SPMD programs will silently diverge (different shapes compile
    different programs; different step counts desynchronize collectives).

    ``values``: pytree of ints/floats/arrays compared by fingerprint. Raises
    ``RuntimeError`` naming the first differing rank. Single-process: no-op.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    leaves = jax.tree_util.tree_leaves(values)
    fp = np.zeros(3, np.float64)
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf, np.float64).ravel()
        nan = ~np.isfinite(a)
        fp[2] += float(nan.sum()) * (i + 1)  # NaN/inf count, not value (NaN != NaN)
        a = np.where(nan, 0.0, a)
        # position-weighted: permutations/transposes of the same values must
        # NOT collide (a plain sum is permutation-invariant)
        fp[0] += float((a * (np.arange(a.size) + 1.0)).sum()) * (i + 1)
        fp[1] += float(a.size) * (i + 1) + len(leaves)
    all_fp = multihost_utils.process_allgather(fp)
    mine = all_fp[jax.process_index()]
    for r, other in enumerate(all_fp):
        if not np.allclose(other, mine):
            raise RuntimeError(
                f"assert_same_across_ranks('{name}'): rank {r} disagrees with "
                f"rank {jax.process_index()} (fingerprints {other} vs {mine}) — "
                f"SPMD divergence")


def in_program_rank_check(x, axis_name):
    """In-program variant: max-minus-min over the axis must be 0 if every
    device computed the same scalar (the reference's ``CheckOverflow``-style
    cross-replica validation). Returns a bool scalar usable in ``jnp.where`` /
    assert-style masking inside jit."""
    import jax.numpy as jnp

    hi = jax.lax.pmax(x, axis_name)
    lo = jax.lax.pmin(x, axis_name)
    return (hi - lo) == jnp.zeros_like(x)


@contextmanager
def comms_profiling(config):
    comms_logger.configure(config)
    try:
        yield comms_logger
    finally:
        comms_logger.log_summary()
