"""Compressed (1-bit / int8) all-reduce with error feedback.

TPU-native equivalent of the reference's compressed collectives
(``runtime/comm/nccl.py:54`` ``compressed_allreduce``: cupy sign-packing, a
two-phase alltoall+allgather exchange, worker- and server-side error
compensation — the engine of 1-bit Adam/LAMB, ``runtime/fp16/onebit/``).

The algorithm is the same two-phase scheme, expressed as XLA collectives
inside ``shard_map`` (EQuARX-style — quantize before the wire, not after):

  phase 1 (reduce-scatter, compressed): each device splits its tensor into
    world chunks, quantizes ``chunk + worker_error``, ``all_to_all``s the
    quantized payloads + scales, dequantizes and reduces its own chunk;
  phase 2 (all-gather, compressed): the reduced chunk is quantized again
    (``server_error`` feedback), ``all_gather``ed, dequantized everywhere.

Error feedback keeps both quantization residuals locally so the *expected*
update is unbiased — the property 1-bit Adam's convergence proof needs.

Wire cost per device: 2 x N/world quantized payloads (1 or 8 bits) instead of
2 x N x 32 bits for a ring allreduce — the same 16-32x compression the
reference claims for its NCCL backend.

The quantize/dequantize kernels and the two collective phases live in
``comm/collectives.py`` (shared with the ZeRO-3 quantized weight gathers);
this module keeps the 1-bit Adam composition and its entry points.
"""

import jax
import jax.numpy as jnp

from .collectives import all_gather_quantized_ef, reduce_scatter_quantized


def compressed_allreduce_local(x, worker_error, server_error, axis_name,
                               bits=1):
    """Inside shard_map: compressed mean-allreduce of per-device ``x`` [n].

    Returns (mean_reduced [n], new_worker_error, new_server_error). n must be
    divisible by the axis size.
    """
    world = jax.lax.axis_size(axis_name)
    n = x.shape[-1]
    if n % world:
        raise ValueError(f"compressed allreduce length {n} not divisible by "
                         f"world {world}")

    # ---- phase 1: compressed reduce-scatter via all_to_all
    mine, new_worker_error = reduce_scatter_quantized(
        x, axis_name, worker_error, bits=bits)

    # ---- phase 2: compressed all-gather of the reduced chunk
    out, new_server_error = all_gather_quantized_ef(
        mine, axis_name, server_error, bits=bits)
    return out, new_worker_error, new_server_error


def make_compressed_allreduce(mesh, axis_name, bits=1):
    """Eager-friendly wrapper: pytree-of-per-device-values -> compressed mean
    over ``axis_name``; carries error state pytrees. Built on shard_map so the
    all_to_all/all_gather appear in the compiled HLO."""
    from jax.sharding import PartitionSpec as P

    def one(x, we, se):
        return compressed_allreduce_local(x, we, se, axis_name, bits=bits)

    sm = jax.shard_map(
        one, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P(axis_name), P(axis_name)),
        axis_names={axis_name}, check_vma=False)
    return sm


def init_error_state(local_len, world):
    """Zero worker/server error buffers for one flattened gradient of
    per-device length ``local_len`` (server error covers one chunk)."""
    return (jnp.zeros((local_len,), jnp.float32),
            jnp.zeros((local_len // world,), jnp.float32))
