"""Quantized / compute-dtype collectives: the wire-bytes layer.

One home for every "change the payload dtype before it crosses the wire"
primitive, consumed by three clients:

- the ZeRO-3 ``per_layer`` weight gathers (``models/transformer.py``): bf16
  cast-then-gather and ZeRO++-style (arXiv:2306.10209 qwZ) int8 blockwise
  quantized gathers — fp32 masters stay sharded, only the 16/8-bit payload
  moves;
- the 1-bit Adam compressed allreduce (``comm/compressed.py``), whose
  quantization kernels were promoted here.

(The engine's ``grad_reduce_dtype`` bf16 reduction lives in auto-sharding
land — a cast BEFORE the ZeRO-2 sharding constraint in
``runtime/engine.py``, verified on the wire by the collective audit — so it
does not call ``reduce_scatter_cast``; that primitive is the manual
(shard_map) counterpart for callers composing their own collectives.)

All ``*_local`` functions run INSIDE a ``shard_map`` body (they call
``jax.lax`` collectives with an axis name). The quantizers are plain jittable
functions. EQuARX (arXiv:2506.17615) is the design reference: quantize before
the wire, as part of the collective, not after.

Precision notes:
- bf16 gather: weights are rounded once to the compute dtype before the
  gather — bitwise identical to the "gather fp32 then cast" program whenever
  the consumer casts to the same dtype (pinned by test_zero3_gather_impl).
- int8 gather: symmetric per-block scales (``block`` elements per fp32
  scale); wire cost ~ ``1 + 4/block`` bytes/param. The backward is a
  straight-through estimator: cotangents reduce-scatter at their own
  (compute) dtype, so gradients never see the quantization rounding.
- error feedback (``quantize(..., error=...)``) keeps the residual local so
  the EXPECTED payload is unbiased across steps — required for compressed
  gradient reductions (1-bit Adam's convergence proof), unnecessary for
  weight gathers (masters are exact; the rounding is a forward perturbation,
  not an accumulating one).
"""

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# quantizers (promoted from comm/compressed.py)
# ---------------------------------------------------------------------------

def quantize(x, bits, error=None):
    """Row-wise symmetric quantization over the last axis.

    ``x [..., n] -> (payload int8, scale f32 [..., 1])``. 1-bit: sign *
    mean(|x|); 8-bit: symmetric linear to int8. With ``error`` (same shape as
    ``x``), quantizes ``x + error`` and ALSO returns the new residual:
    ``(q, scale, new_error)``.
    """
    if error is not None:
        x = x + error
    if bits == 1:
        scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)
        q = jnp.where(x >= 0, jnp.int8(1), jnp.int8(-1))
    else:
        scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
        safe = jnp.maximum(scale, 1e-30)
        q = jnp.clip(jnp.round(x / safe), -127, 127).astype(jnp.int8)
    if error is not None:
        return q, scale, x - dequantize(q, scale, bits)
    return q, scale


def dequantize(q, scale, bits):
    del bits  # same affine map for 1- and 8-bit payloads
    return q.astype(jnp.float32) * scale


def _effective_block(n, block):
    """Largest usable block: ``block`` when it divides n, else the whole row
    (one scale) — keeps every leaf shape legal without padding."""
    return block if (block > 0 and n % block == 0) else n


def quantize_blockwise(x, block=256):
    """ZeRO++-style symmetric int8 with per-block fp32 scales (last axis).

    ``x [..., n] -> (q int8 [..., n], scale f32 [..., n // b])`` where ``b``
    is ``block`` (or ``n`` when ``block`` does not divide ``n``).
    """
    n = x.shape[-1]
    b = _effective_block(n, block)
    g = x.reshape(*x.shape[:-1], n // b, b).astype(jnp.float32)
    scale = jnp.max(jnp.abs(g), axis=-1, keepdims=True) / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(g / safe), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale[..., 0]


def dequantize_blockwise(q, scale, dtype=jnp.float32):
    """Inverse of ``quantize_blockwise`` (block size inferred from shapes)."""
    n = q.shape[-1]
    blocks = scale.shape[-1]
    g = q.reshape(*q.shape[:-1], blocks, n // blocks).astype(jnp.float32)
    return (g * scale[..., None]).reshape(q.shape).astype(dtype)


# ---------------------------------------------------------------------------
# collective primitives (call inside shard_map)
# ---------------------------------------------------------------------------

def all_gather_cast(x, axis_name, axis=0, wire_dtype=None, out_dtype=None):
    """All-gather with the payload cast to ``wire_dtype`` BEFORE the wire.

    The cast-then-gather order is the whole point: expressed as an explicit
    ``jax.lax.all_gather`` of the already-cast operand, it cannot be undone
    by sharding propagation (a ``with_sharding_constraint`` chain can — the
    partitioner reshards the convert's input; PERF.md "known 2x").
    """
    if wire_dtype is not None:
        x = x.astype(wire_dtype)
    out = jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)
    if out_dtype is not None:
        out = out.astype(out_dtype)
    return out


def all_gather_quantized(x, axis_name, axis=0, block=256, out_dtype=None):
    """int8 blockwise-quantized all-gather (ZeRO++ qwZ shape).

    Quantizes the LOCAL shard, gathers the int8 payload and the fp32 scales
    (two collectives, ~``1 + 4/block`` bytes/param on the wire), dequantizes
    everywhere. Differentiable via straight-through: the backward is a plain
    ``psum_scatter`` of the cotangent at its own dtype — gradients never see
    the rounding.
    """
    out_dtype = out_dtype or x.dtype

    @jax.custom_vjp
    def _qgather(v):
        return _fwd(v)[0]

    def _fwd(v):
        q, scale = quantize_blockwise(v, block=block)
        qg = jax.lax.all_gather(q, axis_name, axis=axis, tiled=True)
        sg = jax.lax.all_gather(scale, axis_name,
                                axis=min(axis, scale.ndim - 1), tiled=True)
        return dequantize_blockwise(qg, sg, dtype=out_dtype), None

    def _bwd(_, g):
        return (jax.lax.psum_scatter(
            g, axis_name, scatter_dimension=axis, tiled=True).astype(x.dtype),)

    _qgather.defvjp(_fwd, _bwd)
    return _qgather(x)


def reduce_scatter_cast(x, axis_name, axis=0, wire_dtype=None, out_dtype=None):
    """Reduce-scatter with the payload cast to ``wire_dtype`` first (the
    reduction itself then runs at the wire dtype — document the precision)."""
    if wire_dtype is not None:
        x = x.astype(wire_dtype)
    out = jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                               tiled=True)
    if out_dtype is not None:
        out = out.astype(out_dtype)
    return out


def reduce_scatter_quantized(x, axis_name, error, bits=8):
    """Compressed mean reduce-scatter with worker-side error feedback.

    Phase 1 of the 1-bit Adam exchange: split the local tensor into world
    chunks, quantize ``chunk_i + error``, ``all_to_all`` payloads + scales,
    dequantize and mean-reduce own chunk. ``x [n]`` (n divisible by world) ->
    ``(mean_chunk [n/world], new_error [n])``.
    """
    world = jax.lax.axis_size(axis_name)
    chunks = x.reshape(world, x.shape[-1] // world)
    q, scale, new_error = quantize(chunks, bits,
                                   error=error.reshape(chunks.shape))
    q_recv = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    s_recv = jax.lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0,
                                tiled=False)
    mine = jnp.sum(dequantize(q_recv, s_recv, bits), axis=0) / world
    return mine, new_error.reshape(-1)


def all_gather_quantized_ef(x, axis_name, error, bits=8):
    """Compressed all-gather with (server-side) error feedback.

    Phase 2 of the 1-bit Adam exchange: quantize ``x + error``, gather the
    payload + scale, dequantize everywhere. ``x [m]`` -> ``(gathered
    [world * m], new_error [m])``.
    """
    q, scale, new_error = quantize(x[None, :], bits, error=error[None, :])
    q_all = jax.lax.all_gather(q[0], axis_name)
    s_all = jax.lax.all_gather(scale[0], axis_name)
    out = dequantize(q_all, s_all, bits).reshape(-1)
    return out, new_error[0]
