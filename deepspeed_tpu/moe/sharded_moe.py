"""Mixture-of-Experts: top-k gating with capacity + expert-parallel dispatch.

TPU-native equivalent of the reference's ``deepspeed/moe/sharded_moe.py``:
``TopKGate`` (reference ``:420``), ``top1gating``/``top2gating`` (``:179``/``:277``)
and the ``_AllToAll`` autograd function (``:90``). The reference dispatches tokens
with an explicit ``dist.all_to_all_single`` between expert-parallel ranks; here the
dispatch/combine are einsums in the GShard formulation and XLA's SPMD partitioner
emits the all_to_all when token groups are sharded over ``data`` and the expert
dim over the ``expert`` mesh axis.

Formulation (GShard / Switch):
- tokens keep their [batch, seq] layout; each batch row is a dispatch *group* with
  its own capacity (capacity is per-group, so dispatch is local math — no global
  sort, no dynamic shapes);
- ``dispatch`` [b, s, E, C] (bool) routes token s of group b to slot c of expert e;
  ``combine`` [b, s, E, C] carries the gate weights for the weighted sum back;
- expert compute runs on [E, b, C, m] — sharded (expert, data) — so the
  data->expert resharding before/after is exactly the reference's all_to_all pair;
- the load-balancing aux loss is the Switch/GShard ``E * sum(f_e * p_e)`` term
  (reference ``sharded_moe.py:229``), returned to be added to the model loss.
"""

import dataclasses

import jax
import jax.numpy as jnp

from ..models.layers import Param, normal_init


def _dense_cfg(cfg):
    """Config for the PR-MoE residual branch: the same block geometry with the
    experts turned off (so the dense ``_mlp_init``/``_mlp_apply`` run)."""
    return dataclasses.replace(cfg, n_experts=0)


def expert_capacity(seq_len, n_experts, top_k, capacity_factor, min_capacity=4):
    """Per-group expert capacity (reference ``sharded_moe.py:179`` capacity calc)."""
    cap = int(capacity_factor * seq_len * top_k / n_experts)
    return max(cap, min_capacity)


def top_k_gating(logits, top_k, capacity, *, rng=None, noise_std=0.0,
                 rsample=False, use_rts=False):
    """Top-k gating with per-group capacity.

    Args:
      logits: [b, s, E] router logits (fp32).
      top_k: 1 or 2 (reference supports k in {1, 2}; we allow any k < E).
      capacity: C slots per expert per group.
      rng: optional rng for gating noise (reference's ``noisy_gate_policy``).
      noise_std: stddev of gaussian noise added to logits before top-k.
      rsample: reference 'RSample' policy (``sharded_moe.py:188``): gumbel
        noise on the SELECTION logits only; gate weights stay clean.
      use_rts: Random Token Selection (``sharded_moe.py:220``): the first
        choice's capacity overflow is dropped by random priority instead of
        sequence order, so late-sequence tokens aren't systematically dropped.

    Returns:
      dispatch: [b, s, E, C] bool — token -> (expert, slot) routing.
      combine: [b, s, E, C] float32 — gate weights for the return combine.
      aux_loss: scalar load-balancing loss (Switch: E * sum(f_e * p_e)).
    """
    b, s, E = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)  # [b, s, E]

    gauss_rng = gumbel_rng = rts_rng = None
    if rng is not None:
        gauss_rng, gumbel_rng, rts_rng = jax.random.split(rng, 3)
    select_logits = logits
    if noise_std > 0.0 and gauss_rng is not None:
        select_logits = logits + jax.random.normal(gauss_rng, logits.shape) * noise_std
    if rsample and gumbel_rng is not None:
        select_logits = select_logits + jax.random.gumbel(gumbel_rng, logits.shape)

    # iteratively pick k experts per token, masking previous picks
    masked = select_logits
    expert_masks = []   # k x [b, s, E] one-hot
    expert_gates = []   # k x [b, s]
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                      # [b, s]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)     # [b, s, E]
        expert_masks.append(onehot)
        expert_gates.append(jnp.sum(gates * onehot, axis=-1))  # [b, s]
        masked = jnp.where(onehot > 0, -jnp.inf, masked)

    # aux loss from the top-1 assignment (reference top1gating:229 / top2gating:303)
    me = jnp.mean(gates, axis=(0, 1))           # [E] mean router prob
    ce = jnp.mean(expert_masks[0], axis=(0, 1)) # [E] fraction of tokens -> expert
    aux_loss = E * jnp.sum(me * ce)

    # position of each token within its expert's queue, counted across the k
    # choices in priority order (choice 0 gets slots first, as in top2gating where
    # locations2 += sum(mask1))
    dispatch = jnp.zeros((b, s, E, capacity), jnp.bool_)
    combine = jnp.zeros((b, s, E, capacity), jnp.float32)
    prior_counts = jnp.zeros((b, E), jnp.float32)  # slots consumed by higher choices
    denom = jnp.zeros((b, s), jnp.float32)
    kept_masks = []
    for choice, (mask, gate) in enumerate(zip(expert_masks, expert_gates)):
        if use_rts and rts_rng is not None and choice == 0:
            # random priority (reference mask1 * uniform -> _top_idx): rank
            # each token among its expert's tokens by a random draw. Done by
            # sorting into priority order, cumsumming, and scattering back —
            # O(s log s), no [s, s] pairwise matrix.
            r = jax.random.uniform(rts_rng, (b, s))
            perm = jnp.argsort(r, axis=1)                        # priority order
            mask_sorted = jnp.take_along_axis(mask, perm[:, :, None], axis=1)
            pos_sorted = jnp.cumsum(mask_sorted, axis=1) - mask_sorted
            inv = jnp.argsort(perm, axis=1)
            pos_in_expert = jnp.take_along_axis(pos_sorted, inv[:, :, None], axis=1)
        else:
            # cumulative position of this token in expert's queue in its group
            pos_in_expert = jnp.cumsum(mask, axis=1) - mask    # [b, s, E]
        pos = pos_in_expert + prior_counts[:, None, :]
        keep = mask * (pos < capacity)                         # drop overflow tokens
        kept_masks.append((keep, gate))
        prior_counts = prior_counts + jnp.sum(keep, axis=1)
        slot = jnp.sum(pos * keep, axis=-1)                    # [b, s]
        slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # [b, s, C]
        routed = keep[..., None] * slot_oh[:, :, None, :]      # [b, s, E, C]
        dispatch = dispatch | (routed > 0)
        combine = combine + routed * gate[..., None, None]
        denom = denom + gate * jnp.sum(keep, axis=-1)

    # normalize combine weights over the kept choices (top2gating:321 renormalize)
    combine = combine / jnp.maximum(denom, 1e-9)[..., None, None]
    return dispatch, combine, aux_loss


def moe_mlp_init(rng, cfg):
    """Expert-stacked MLP params: leading "expert" logical axis (sharded over the
    ``expert`` mesh axis) + router. Mirrors the reference's ``Experts`` module
    (``moe/experts.py``) holding E copies of the FFN. For ``swiglu`` models the
    experts are gated — silu(x @ wi_gate) ⊙ (x @ wi) — matching the dense FFN's
    silu(gate) * up convention (models/transformer.py)."""
    E = cfg.n_experts
    k_router, k1, k2, k3, k_res, k_coef = jax.random.split(rng, 6)
    std = cfg.initializer_range
    out_std = std / (2.0 * cfg.n_layers) ** 0.5
    params = {
        "router": {
            "kernel": Param(normal_init(k_router, (cfg.d_model, E), std),
                            ("embed", "expert_logits"))
        },
        "wi": Param(normal_init(k1, (E, cfg.d_model, cfg.d_ff), std),
                    ("expert", "embed", "mlp")),
        "wo": Param(normal_init(k2, (E, cfg.d_ff, cfg.d_model), out_std),
                    ("expert", "mlp", "embed")),
    }
    if cfg.activation == "swiglu":
        params["wi_gate"] = Param(normal_init(k3, (E, cfg.d_model, cfg.d_ff), std),
                                  ("expert", "embed", "mlp"))
    if cfg.moe_use_residual:
        # PR-MoE (reference moe/layer.py:16 use_residual): a dense MLP beside
        # the experts + a learned 2-way blend coefficient
        from ..models.transformer import _mlp_init

        params["res_mlp"] = _mlp_init(k_res, _dense_cfg(cfg))
        params["coef"] = {
            "kernel": Param(normal_init(k_coef, (cfg.d_model, 2), std),
                            ("embed", "coef")),
            "bias": Param(jnp.zeros((2,), jnp.float32), ("coef",)),
        }
    return params


def moe_mlp_apply(cfg, p, x, *, deterministic=True, rng=None):
    """MoE FFN. x: [b, s, m] -> (y [b, s, m], aux_loss scalar).

    The two big einsums below are the all_to_all pair: ``expert_in`` reshards from
    token-sharded (data) to expert-sharded layout and ``y`` back again.
    """
    from ..models import layers as L

    b, s, m = x.shape
    E = cfg.n_experts
    if deterministic:
        # Eval/decode: default to drop-free capacity (C = s covers the worst-case
        # all-tokens-to-one-expert) so KV-cache decode is exactly consistent with
        # the full forward; an explicit eval factor trades memory for drops.
        if cfg.moe_eval_capacity_factor and cfg.moe_eval_capacity_factor > 0:
            capacity = expert_capacity(s, E, cfg.moe_top_k,
                                       cfg.moe_eval_capacity_factor,
                                       cfg.moe_min_capacity)
        else:
            capacity = s
    else:
        capacity = expert_capacity(s, E, cfg.moe_top_k, cfg.moe_capacity_factor,
                                   cfg.moe_min_capacity)

    policy = (cfg.moe_noisy_gate_policy or "").lower()
    gate_in = x.astype(jnp.float32)
    gate_rng = rng
    if policy == "jitter" and not deterministic and rng is not None:
        # reference multiplicative_jitter (sharded_moe.py:49): scale the gate
        # INPUT by uniform(1±eps) — the router sees jittered activations
        jitter_rng, gate_rng = jax.random.split(rng)
        gate_in = gate_in * jax.random.uniform(
            jitter_rng, gate_in.shape, minval=1.0 - 1e-2, maxval=1.0 + 1e-2)
    router_logits = jnp.einsum(
        "bsm,me->bse", gate_in, p["router"]["kernel"].astype(jnp.float32)
    )
    noise = cfg.moe_noise_std if not deterministic else 0.0
    dispatch, combine, aux = top_k_gating(
        router_logits, cfg.moe_top_k, capacity, rng=gate_rng, noise_std=noise,
        rsample=(policy == "rsample" and not deterministic),
        use_rts=(cfg.moe_use_rts and not deterministic),
    )
    dispatch_f = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    # data-sharded [b,s,..] -> expert-sharded [E,b,C,..]: the all_to_all.
    # Without an explicit constraint XLA is free to keep the [E,b,C,m]
    # intermediates replicated-E / sharded-b (turning the resharding pair into
    # all_reduces); pinning E over ``expert`` and b over ``data`` forces the
    # partitioner to emit the true all_to_all of the reference's ``_AllToAll``
    # autograd fn (``deepspeed/moe/sharded_moe.py:90``).
    expert_in = jnp.einsum("bsec,bsm->ebcm", dispatch_f, x)
    expert_in = _expert_a2a(expert_in, getattr(cfg, "mesh", None), to_expert=True)
    w_i = p["wi"].astype(x.dtype)
    w_o = p["wo"].astype(x.dtype)
    if cfg.activation == "swiglu":
        # same convention as the dense MLP (models/transformer.py): silu on the
        # projection named "gate", elementwise with the ungated up-projection wi
        w_g = p["wi_gate"].astype(x.dtype)
        h = (jax.nn.silu(jnp.einsum("ebcm,emf->ebcf", expert_in, w_g))
             * jnp.einsum("ebcm,emf->ebcf", expert_in, w_i))
    else:
        act = L.ACTIVATIONS[cfg.activation]
        h = act(jnp.einsum("ebcm,emf->ebcf", expert_in, w_i))
    expert_out = jnp.einsum("ebcf,efm->ebcm", h, w_o)
    expert_out = _expert_a2a(expert_out, getattr(cfg, "mesh", None), to_expert=False)
    # expert-sharded -> data-sharded: the return all_to_all
    y = jnp.einsum("bsec,ebcm->bsm", combine, expert_out)
    if cfg.moe_use_residual:
        # PR-MoE blend (reference moe/layer.py:118): out*c0 + dense(x)*c1
        from ..models.transformer import _mlp_apply

        res_p = jax.tree_util.tree_map(
            lambda a: a.astype(x.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p["res_mlp"])
        dense = _mlp_apply(_dense_cfg(cfg), res_p, x).astype(x.dtype)
        coef = jax.nn.softmax(
            x.astype(jnp.float32) @ p["coef"]["kernel"].astype(jnp.float32)
            + p["coef"]["bias"].astype(jnp.float32), axis=-1).astype(x.dtype)
        y = y * coef[..., 0:1] + dense * coef[..., 1:]
    return y, aux * cfg.moe_aux_loss_weight


def _expert_a2a(x, mesh, *, to_expert):
    """Force the data<->expert reshard of an [E, b, C, m] intermediate to compile
    to a true all_to_all.

    A single target constraint lets XLA's partitioner fold the reshard into its
    einsum strategy (which it resolves with all-gathers, replicating the E dim —
    O(tokens*E) traffic). Pinning BOTH endpoint layouts makes the reshard an
    explicit tensor-resharding step — the "expert" mesh axis moves between dim 0
    (E) and dim 1 (b) — which the partitioner lowers to the all_to_all of the
    reference's ``_AllToAll`` (``deepspeed/moe/sharded_moe.py:90``). Verified in
    tests/unit/test_moe.py::test_moe_dispatch_emits_all_to_all against HLO.

    No-op when there is no mesh / no expert axis / indivisible shapes — single
    -device tests and dense paths compile unchanged.
    """
    if mesh is None:
        return x
    from ..parallel.topology import DATA_AXIS, EXPERT_AXIS

    P = jax.sharding.PartitionSpec
    ep = mesh.shape.get(EXPERT_AXIS, 1)
    dp = mesh.shape.get(DATA_AXIS, 1)
    E, b = x.shape[0], x.shape[1]
    if ep <= 1 or E % ep or b % (dp * ep):
        return x
    rest = [None] * (x.ndim - 2)
    # tokens-local layout: E replicated, b sharded over the full dp*ep world
    token_spec = P(None, (DATA_AXIS, EXPERT_AXIS), *rest)
    # expert-local layout: E over expert, b over data
    expert_spec = P(EXPERT_AXIS, DATA_AXIS if dp > 1 else None, *rest)
    first, second = ((token_spec, expert_spec) if to_expert
                     else (expert_spec, token_spec))
    x = jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, first))
    return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, second))
