from .sharded_moe import (
    top_k_gating,
    moe_mlp_init,
    moe_mlp_apply,
    expert_capacity,
)

__all__ = ["top_k_gating", "moe_mlp_init", "moe_mlp_apply", "expert_capacity"]
