"""The training engine.

TPU-native equivalent of the reference's ``DeepSpeedEngine`` (``runtime/engine.py:183``):
a config-driven wrapper exposing ``forward`` / ``backward`` / ``step`` /
``save_checkpoint`` / ``load_checkpoint`` plus a fused ``train_batch``. The torch
version orchestrates hooks, buckets, and streams at runtime; here the whole training
step is a handful of jitted XLA programs whose sharding specs realize the configured
parallelism (see ``parallel/sharding.py`` for the ZeRO-stage -> spec mapping):

- params: fp32 master copies (reference keeps the same fp32 master in
  ``fp16/fused_optimizer.py``), sharded per ZeRO-3 / TP, donated through the step
- compute: bf16/fp16 cast at apply time (``fp16``/``bf16`` config sections)
- grads: accumulated in a persistent buffer sharded per ZeRO-2
- optimizer state: sharded per ZeRO-1
- fp16: dynamic loss scaling with in-program overflow check and step skip
  (reference ``runtime/fp16/loss_scaler.py`` + ``CheckOverflow``)

Init sequence mirrors the reference (``engine.py:186-380``): dist init -> config
parse -> mesh ("distributed model") -> optimizer -> lr scheduler -> checkpointing.
Parameter init happens *sharded*: ``model.init`` runs under jit with the ZeRO specs
as out_shardings, so a 13B model never materializes unsharded — the reference needs
the ``zero.Init`` monkey-patch context (``partition_parameters.py:601``) for this.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..config import load_config, ConfigError
from ..models.layers import Param, split_params_axes
from ..ops import (
    get_optimizer,
    get_lr_schedule,
    make_scaler_state,
    check_overflow,
    update_scale,
    clip_grads_by_global_norm,
    global_grad_norm,
)
from ..parallel import build_mesh, DATA_AXIS, EXPERT_AXIS, PIPE_AXIS
from ..parallel.sharding import (
    param_partition_specs,
    state_partition_specs,
    batch_partition_specs,
    named,
)
from ..utils.logging import log_dist, logger
from ..utils.timer import (
    SynchronizedWallClockTimer,
    ThroughputTimer,
    FORWARD_GLOBAL_TIMER,
    BACKWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
)
from .dataloader import DeepSpeedDataLoader

DTYPES = {"float16": jnp.float16, "bfloat16": jnp.bfloat16, "float32": jnp.float32}

# Compile-only construction switch (see abstract_init below).
_ABSTRACT_INIT = False


class abstract_init:
    """Context manager: engines constructed inside build with ABSTRACT params.

    ``self.params`` / ``self.optimizer_state`` become ``jax.ShapeDtypeStruct``
    trees carrying the real shardings instead of device buffers, so the engine
    can ``lower()``/``compile()`` its train step — AOT memory analysis, HLO
    inspection, collective-volume accounting — without a single byte of model
    state existing anywhere. This is the planning role the reference autotuner
    fills with model-info estimation (``autotuning/autotuner.py``
    ``_get_model_info``), made exact: the numbers come from the real compiled
    program, not a formula. ``tools/scale_projection.py`` uses it to plan
    OPT-13B ZeRO-3 on a 256-chip mesh from a CPU host (materializing the fp32
    master would need ~156 GB of host RAM).

    Execution APIs (``train_batch`` etc.) are unusable on such an engine.
    """

    def __enter__(self):
        global _ABSTRACT_INIT
        self._prev = _ABSTRACT_INIT
        _ABSTRACT_INIT = True
        return self

    def __exit__(self, *exc):
        global _ABSTRACT_INIT
        _ABSTRACT_INIT = self._prev
        return False


def _abstract_tree(shape_tree, shardings):
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shape_tree, shardings)


class DeepSpeedEngine:
    def __init__(self, model, optimizer=None, model_parameters=None, training_data=None,
                 lr_scheduler=None, mesh=None, collate_fn=None, config=None):
        if model is None:
            raise ConfigError("deepspeed_tpu.initialize: model is required")
        self.module = model
        self.client_optimizer = optimizer
        self._config = load_config(config)

        # -- mesh (the reference's _configure_distributed_model + process groups) ---
        self.mesh = mesh if mesh is not None else build_mesh(self._config.mesh)
        self.dp_world_size = self.mesh.shape[DATA_AXIS] * self.mesh.shape.get(EXPERT_AXIS, 1)
        self.mp_world_size = self.mesh.shape.get("model", 1)

        # -- batch triangle ----------------------------------------------------------
        (self.train_batch_size_, self.micro_batch_size,
         self.gradient_accumulation_steps_) = self._config.resolve_batch_size(self.dp_world_size)

        # -- precision ---------------------------------------------------------------
        self.compute_dtype = DTYPES[self._config.mixed_precision_dtype]
        if hasattr(self.module, "config") and hasattr(self.module.config, "compute_dtype"):
            self.module.config.compute_dtype = self.compute_dtype
        if self._config.gradient_checkpointing and hasattr(self.module, "config") \
                and hasattr(self.module.config, "remat"):
            self.module.config.remat = True
        self.fp16_enabled = self._config.fp16.enabled

        self.zero_stage = self._config.zero_optimization.stage
        self._persist_threshold = self._config.zero_optimization.param_persistence_threshold
        # bf16 gradient reduction wire (reduce-scatter at stage >= 2,
        # all-reduce below): grads are cast BEFORE the sharding constraint so
        # the collective moves 16-bit payloads; accumulation across
        # micro-batches then also runs at the wire dtype. fp32 = exact.
        self._grad_wire_dtype = jnp.bfloat16 \
            if self._config.zero_optimization.grad_reduce_dtype == "bf16" \
            else None
        # validated regardless of gather mode: a typo'd knob must fail at
        # construction, not lie dormant until per_layer is enabled
        if self._config.zero_optimization.zero3_gather_impl not in (
                "constraint", "shard_map"):
            raise ConfigError(
                f"zero3_gather_impl must be 'constraint' or 'shard_map', got "
                f"{self._config.zero_optimization.zero3_gather_impl!r}")

        # -- pipeline parallelism ----------------------------------------------------
        # With pipe > 1 the whole accumulation window runs as ONE compiled GPipe
        # sweep (parallel/pipeline.py): pipeline microbatches = the configured
        # gradient_accumulation_steps (the reference folds grad-accum into the 1F1B
        # schedule the same way, pipe/engine.py:285 train_batch).
        self.pipe_stages = self.mesh.shape.get(PIPE_AXIS, 1)
        self._pipe_microbatches = 1

        # -- sequence parallelism (ring attention over the seq axis) -----------------
        self.seq_parallel_size = self.mesh.shape.get("seq", 1)
        if self.seq_parallel_size > 1:
            if not (hasattr(self.module, "config")
                    and hasattr(self.module.config, "sequence_parallel")):
                raise ConfigError(
                    "sequence parallelism (mesh seq > 1) requires a model whose "
                    "config supports sequence_parallel (the transformer backbone)"
                )
            self.module.config.sequence_parallel = True
        if self.pipe_stages > 1:
            if not (hasattr(self.module, "config")
                    and hasattr(self.module.config, "pipeline_stages")):
                raise ConfigError(
                    "pipeline parallelism (mesh pipe > 1) requires a model whose "
                    "config supports pipeline_stages (the transformer backbone)"
                )
            self._pipe_microbatches = self.gradient_accumulation_steps_
            self.gradient_accumulation_steps_ = 1
            self.module.config.pipeline_stages = self.pipe_stages
            self.module.config.pipeline_microbatches = self._pipe_microbatches
        # Hand the mesh to the model whenever its config can carry it: ring
        # attention (seq), the pipeline loop (pipe), and the MoE dispatch
        # constraints (expert; moe/sharded_moe.py _expert_a2a) all need it.
        if hasattr(self.module, "config") and hasattr(self.module.config, "mesh"):
            self.module.config.mesh = self.mesh
        elif self.mesh.shape.get(EXPERT_AXIS, 1) > 1:
            logger.warning(
                "mesh has expert>1 but the model config has no `mesh` field: MoE "
                "dispatch cannot be constrained to all_to_all and will compile "
                "to a degraded replicated layout")
        if self.mp_world_size > 1 and hasattr(self.module, "config") \
                and getattr(self.module.config, "fused_qkv", False):
            # the SPMD partitioner miscompiles jnp.concatenate along an axis
            # the operands are sharded on (verified wrong bytes on jaxlib
            # 0.4.x), which is exactly the fused-qkv concat under a >1 model
            # axis; the unfused projections are the Megatron column-parallel
            # form and bitwise-identical per output column
            self.module.config.fused_qkv = False
            log_dist("tensor parallelism: fused qkv disabled (sharded-concat "
                     "SPMD hazard); using per-projection matmuls", ranks=[0])

        # -- compression-in-training (reference compression_training section) --------
        self._compression = None
        self._compression_phase = None
        self._compression_step = 0
        if self._config.compression_training:
            # reject incompatible configs BEFORE touching the module config —
            # a caught ConfigError must leave the model reusable
            if self.pipe_stages > 1:
                raise ConfigError(
                    "compression_training does not compose with pipeline "
                    "parallelism (apply compression manually via "
                    "deepspeed_tpu.compression on pipe meshes)")
            if dict(self._config.compression_training).get(
                    "layer_reduction", {}).get("enabled"):
                raise ConfigError(
                    "compression_training.layer_reduction is a deploy-time "
                    "transform (redundancy_clean slices the layer stack); it "
                    "cannot run inside training — train the full depth, then "
                    "clean, or build the student model directly")
            if self._config.gradient_compression.enabled or \
                    self._config.optimizer.type.lower().replace("-", "").replace("_", "") \
                    in ("onebitadam", "zerooneadam", "onebitlamb"):
                raise ConfigError(
                    "compression_training does not compose with 1-bit/"
                    "compressed-gradient optimizers (their train path would "
                    "silently skip the quantization/pruning masks)")
            from ..compression import apply_to_model_config, init_compression

            if hasattr(self.module, "config"):
                # activation quantization is a model-config knob (QuantAct role)
                self.module.config = apply_to_model_config(
                    self.module.config, self._config.compression_training)
            self._compression = init_compression(
                self._config.compression_training,
                model_config=getattr(self.module, "config", None))

        # -- parameters (sharded at init = zero.Init) --------------------------------
        self._rng = jax.random.PRNGKey(self._config.seed)
        self._init_parameters(model_parameters)

        # -- optimizer ---------------------------------------------------------------
        self._configure_optimizer()

        if self._compression is not None and self._onebit_active:
            # authoritative guard: a client-PASSED 1-bit optimizer instance
            # bypasses the config-string check above, and the 1-bit train
            # path would silently skip the compression masks
            raise ConfigError(
                "compression_training does not compose with 1-bit/"
                "compressed-gradient optimizers (their train path would "
                "silently skip the quantization/pruning masks)")

        # -- lr scheduler ------------------------------------------------------------
        self.lr_scheduler = lr_scheduler
        if self.lr_scheduler is None and self._config.scheduler.type:
            self.lr_scheduler = get_lr_schedule(
                self._config.scheduler.type, self._config.scheduler.params
            )

        # -- fp16 loss scaler --------------------------------------------------------
        fp16 = self._config.fp16
        self._scaler_meta = make_scaler_state(
            static_scale=fp16.loss_scale,
            initial_scale_power=fp16.initial_scale_power,
            min_scale=fp16.min_loss_scale,
        ) if self.fp16_enabled else None
        if self.fp16_enabled:
            self._scale = self._scaler_meta["scale"]
            self._good_steps = self._scaler_meta["good_steps"]
        else:
            self._scale = jnp.asarray(1.0, jnp.float32)
            self._good_steps = jnp.zeros((), jnp.int32)

        # -- grad accumulation buffer (ZeRO-2 sharded) -------------------------------
        self._grad_specs = state_partition_specs(
            self._axes, self._shapes, self.mesh,
            zero_stage=self.zero_stage if self.zero_stage >= 2 else 0,
            min_data_shard_elems=self._persist_threshold if self.zero_stage >= 2 else 2 ** 62,
        )
        self._grad_shardings = named(self.mesh, self._grad_specs)
        self._acc_grads = None
        self._cached = None  # (loss, grads) from the last forward

        # -- counters / timers / monitor / telemetry ---------------------------------
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._last_resume_rescaled = False  # set by load_checkpoint
        tel = self._config.telemetry
        from ..telemetry import SpanTracer

        # device_sync arms a block_until_ready fence on BOTH the span ends
        # and the fwd/bwd/step timers: unsynced host timers measure dispatch
        # (jax's async enqueue), not execution
        self._telemetry_sync = bool(tel.enabled and tel.device_sync)
        sync_fn = self._device_fence if tel.device_sync else None
        self.tracer = SpanTracer.from_config(
            tel, sync_fn=self._device_fence,
            meta={"process": "train", "mesh": dict(self.mesh.shape),
                  "zero_stage": self.zero_stage})
        self.timers = SynchronizedWallClockTimer(sync_fn=sync_fn)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size_, steps_per_output=self._config.steps_per_print,
            sync_fn=sync_fn,
        )
        self._wall_clock_breakdown = self._config.wall_clock_breakdown
        from ..monitor.monitor import MonitorMaster

        self.monitor = MonitorMaster(self._config)

        # -- numerics flight recorder (telemetry/health.py) ----------------------
        # Group definitions derive from the param pytree; the in-graph stats
        # are ALWAYS a side output of the compiled step (so the sanitizer/
        # budget gates audit the real program), but the host-side monitor
        # only reads them — one sync per observed step — when enabled.
        from ..telemetry.health import HealthMonitor, derive_group_names

        self._health_groups = derive_group_names(
            self._shapes, is_leaf=lambda x: isinstance(x, tuple))
        self.health = HealthMonitor(
            self._config.health, self._health_groups, monitor=self.monitor,
            meta={"process": "train", "mesh": dict(self.mesh.shape),
                  "zero_stage": self.zero_stage})
        # skip_step is the one action realized IN-GRAPH: generalize the fp16
        # overflow-skip to any-dtype non-finite grads (pre-update, so the
        # poisoned step never touches params/optimizer state)
        self._health_skip = bool(
            self._config.health.enabled
            and self._config.health.nonfinite_action == "skip_step")
        self._health_fn = None  # lazy jitted stats for the offloaded path
        self._health_rng = None  # key that SEEDED the current step's window

        # -- explicit ZeRO-3 gather schedule (per-layer constraint in the scan) ------
        if (self.zero_stage >= 3
                and self._config.zero_optimization.zero3_gather_mode == "per_layer"
                and hasattr(self.module, "config")
                and hasattr(self.module.config, "zero3_per_layer_gather")
                and isinstance(self.param_specs, dict)
                and "blocks" in self.param_specs):
            gather_specs = jax.tree_util.tree_map(
                lambda s: P(*(None if a == DATA_AXIS else a
                              for a in tuple(s)[1:])),
                self.param_specs["blocks"],
                is_leaf=lambda x: isinstance(x, P))
            self.module.config.zero3_per_layer_gather = True
            self.module.config.zero3_gather_specs = gather_specs
            impl, wire = self._resolve_gather_wire()
            if impl == "shard_map":
                if not hasattr(self.module.config, "zero3_sharded_specs"):
                    # refuse rather than silently run fp32-sized gather wire
                    # while the operator believes the bf16/int8 path is active
                    raise ConfigError(
                        "zero3_gather_impl: 'shard_map' requires a model "
                        "config with a zero3_sharded_specs field (the "
                        "transformer backbone); this module only supports "
                        "the 'constraint' impl")
                self.module.config.zero3_gather_impl = "shard_map"
                if hasattr(self.module.config, "zero3_gather_dtype"):
                    self.module.config.zero3_gather_dtype = wire
                    self.module.config.zero3_gather_block = \
                        self._config.zero_optimization.zero3_gather_block
                elif wire != "compute":
                    # "compute" is the field-less module's historical
                    # behavior; anything EXPLICIT (fp32 included — an
                    # exact-gather baseline silently running bf16 wire is
                    # precisely the mismatch this guard exists for) needs a
                    # config that can carry it
                    raise ConfigError(
                        f"zero3_gather_dtype={wire!r} requires a model config "
                        f"with a zero3_gather_dtype field (the transformer "
                        f"backbone); this module would silently gather at "
                        f"the compute dtype")
                # sharded specs minus the layers dim: the shard_map islands'
                # in_specs (the all_gather's input layout)
                self.module.config.zero3_sharded_specs = \
                    jax.tree_util.tree_map(
                        lambda s: P(*tuple(s)[1:]),
                        self.param_specs["blocks"],
                        is_leaf=lambda x: isinstance(x, P))
            # Top-level params (embedding / head / final norm) need a
            # gather-before-use constraint WHEN their ZeRO-3 shard landed on
            # the d_model ("embed") axis: that axis is the contraction dim of
            # the consuming matmul, and propagating it in makes the
            # partitioner partial-sum full-batch logits with giant
            # all-reduces instead of gathering the 100 MB weight (observed:
            # 8.6 TB/chip temps on the OPT-13B/256 projection, where
            # vocab % 256 != 0 forced logical_to_physical onto d_model).
            # A vocab-axis shard is LEFT ALONE — vocab-parallel CE is the
            # better program (each device computes its logits slice with a
            # full contraction; measured cheaper at dp=8 than gathering).
            # ZeRO-3 discipline either way: masters stay sharded.
            if hasattr(self.module.config, "zero3_toplevel_gather_specs"):
                def _strip_embed_axis(axes, spec):
                    # strip the data shard from every axis EXCEPT vocab: a
                    # vocab shard means vocab-parallel CE (keep); any other
                    # placement (embed, unnamed, seq_table) sits on a
                    # contraction/gather dim of the consumer and must be
                    # gathered before use
                    return P(*(None if (s == DATA_AXIS and a != "vocab")
                               else s
                               for a, s in zip(axes, tuple(spec))))

                is_axes = lambda x: isinstance(x, tuple) and all(
                    isinstance(a, (str, type(None))) for a in x)
                self.module.config.zero3_toplevel_gather_specs = {
                    k: jax.tree_util.tree_map(
                        _strip_embed_axis, self._axes[k], v,
                        is_leaf=is_axes)
                    for k, v in self.param_specs.items() if k != "blocks"}
            log_dist(f"ZeRO-3 gather mode: per_layer (explicit schedule, "
                     f"impl={impl}, wire={wire})", ranks=[0])

        # -- progressive layer drop (reference engine.py:680 PLD hook) ---------------
        self._pld = None
        pld_cfg = self._config.progressive_layer_drop
        if pld_cfg.enabled:
            import inspect

            supported = ("pld_theta"
                         in inspect.signature(self.module.loss).parameters)
            if not supported:
                logger.warning(
                    "progressive_layer_drop enabled but %s.loss has no "
                    "pld_theta parameter; PLD is OFF",
                    type(self.module).__name__)
            elif self._onebit_active or self._offloaded is not None \
                    or self.pipe_stages > 1:
                logger.warning(
                    "progressive_layer_drop only engages on the fused "
                    "train_batch path (not 1-bit/offload/pipeline); PLD is OFF")
            else:
                from .extras import ProgressiveLayerDrop

                self._pld = ProgressiveLayerDrop(theta=pld_cfg.theta,
                                                 gamma=pld_cfg.gamma)
                log_dist(
                    f"Progressive layer drop: theta_bar={pld_cfg.theta} "
                    f"gamma={pld_cfg.gamma}", ranks=[0])

        # -- curriculum learning (reference engine.py:1675 seqlen scheduling) --------
        self._curriculum = None
        cl = self._config.curriculum_learning
        if cl.enabled:
            from .data_pipeline import CurriculumScheduler

            self._curriculum = CurriculumScheduler({
                "curriculum_type": cl.curriculum_type,
                "min_difficulty": cl.min_difficulty,
                "max_difficulty": cl.max_difficulty,
                "schedule_type": cl.schedule_type,
                "schedule_config": dict(cl.schedule_config),
            })
            log_dist(
                f"Curriculum learning: {cl.curriculum_type} "
                f"{cl.min_difficulty}->{cl.max_difficulty} ({cl.schedule_type})",
                ranks=[0])

        # -- dataloader --------------------------------------------------------------
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # -- checkpointing -----------------------------------------------------------
        ckpt_cfg = self._config.checkpoint
        from ..utils.retry import RetryPolicy

        ckpt_retry = RetryPolicy(max_attempts=ckpt_cfg.retries,
                                 base_delay=ckpt_cfg.retry_backoff,
                                 retry_on=(OSError,))
        if ckpt_cfg.engine == "sharded":
            from ..checkpoint.sharded import (AsyncShardedCheckpointEngine,
                                              ShardedCheckpointEngine)

            self.checkpoint_engine = AsyncShardedCheckpointEngine(ckpt_retry) \
                if ckpt_cfg.async_save else ShardedCheckpointEngine(ckpt_retry)
        elif ckpt_cfg.async_save:
            from ..checkpoint.engine import AsyncCheckpointEngine

            self.checkpoint_engine = AsyncCheckpointEngine(ckpt_retry)
        else:
            from ..checkpoint.engine import NpzCheckpointEngine

            self.checkpoint_engine = NpzCheckpointEngine(ckpt_retry)

        # -- compiled functions (built lazily) ---------------------------------------
        self._fwd_bwd_fn = None
        self._accumulate_fn = None
        self._apply_fn = None
        self._train_step_fn = None
        self._eval_fn = None
        self._train_mode = True
        # per-step collective wire stats (comms_logger / collective_wire_stats)
        self._wire_stats = None
        self._last_batch_struct = None
        self._last_loss = None  # unfused path: forward()'s loss for health

        log_dist(
            f"DeepSpeedEngine: mesh={dict(self.mesh.shape)} zero_stage={self.zero_stage} "
            f"dtype={self._config.mixed_precision_dtype} "
            f"batch(total={self.train_batch_size_}, micro={self.micro_batch_size}, "
            f"gas={self.gradient_accumulation_steps_})",
            ranks=[0],
        )
        if self._config.dump_state:
            # reference engine.py dump_state: print the resolved config
            import json as _json

            log_dist("config state:\n" + _json.dumps(
                self._config.to_dict(), indent=2, default=str), ranks=[0])

    # ------------------------------------------------------------------------------
    # init helpers
    # ------------------------------------------------------------------------------
    def _device_fence(self):
        """Zero-arg device fence for spans/timers (``telemetry.device_sync``):
        block on the freshest step output — the cached (loss, grads) right
        after a forward, else the live params the step just rewrote. Abstract
        engines (ShapeDtypeStruct trees) have nothing to block on; the guard
        keeps tracing from ever taking a step down."""
        try:
            jax.block_until_ready(
                self._cached if self._cached is not None else self.params)
        except Exception:
            pass

    def _resolve_gather_wire(self):
        """``zero3_gather_dtype`` -> (impl, wire-dtype name for the model).

        bf16/int8 wires imply the shard_map impl — a constraint chain cannot
        pin the wire dtype (the partitioner reshards an elementwise op's
        input to match its constrained output; PERF.md "known 2x"). "bf16"
        means "the 16-bit compute dtype": under fp16 training the wire is
        fp16. Masters stay sharded fp32 in every mode.
        """
        z = self._config.zero_optimization
        impl, gdtype = z.zero3_gather_impl, z.zero3_gather_dtype
        if gdtype in ("bf16", "int8") and impl != "shard_map":
            log_dist(
                f"zero3_gather_dtype={gdtype!r} implies "
                f"zero3_gather_impl='shard_map' (a sharding-constraint chain "
                f"cannot pin the wire dtype); upgrading", ranks=[0])
            impl = "shard_map"
        if gdtype == "auto":
            wire = "compute" if impl == "shard_map" else "fp32"
        elif gdtype == "bf16":
            wire = "fp16" if self._config.fp16.enabled else "bf16"
        else:
            wire = gdtype  # "fp32" | "int8"
        return impl, wire

    def _init_parameters(self, model_parameters):
        if model_parameters is not None:
            if isinstance(model_parameters, tuple) and len(model_parameters) == 2:
                values, axes = model_parameters
            else:
                values, axes = split_params_axes(model_parameters)
        else:
            # Trace init to get shapes/axes without materializing anything.
            params_shape = jax.eval_shape(self.module.init, self._rng)
            is_param = lambda x: isinstance(x, Param)
            axes = jax.tree_util.tree_map(lambda p: p.axes, params_shape, is_leaf=is_param)
            values = None

        if values is not None:
            shapes = jax.tree_util.tree_map(lambda v: tuple(v.shape), values)
        else:
            shapes = jax.tree_util.tree_map(
                lambda p: tuple(p.value.shape), params_shape,
                is_leaf=lambda x: isinstance(x, Param),
            )

        self._axes = axes
        self._shapes = shapes
        self.param_specs = param_partition_specs(
            axes, shapes, self.mesh, zero_stage=self.zero_stage,
            min_data_shard_elems=self._persist_threshold,
        )
        self.param_shardings = named(self.mesh, self.param_specs)

        if values is None:
            # init directly into the sharded layout: the zero.Init equivalent.
            init_fn = lambda rng: split_params_axes(self.module.init(rng))[0]
            if _ABSTRACT_INIT:
                self.params = _abstract_tree(
                    jax.eval_shape(init_fn, self._rng), self.param_shardings)
            else:
                with self.mesh:
                    self.params = jax.jit(init_fn, out_shardings=self.param_shardings)(self._rng)
        elif _ABSTRACT_INIT:
            self.params = _abstract_tree(
                jax.tree_util.tree_map(
                    lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), values),
                self.param_shardings)
        else:
            self.params = jax.tree_util.tree_map(jax.device_put, values, self.param_shardings)

        n_params = sum(int(np.prod(s)) for s in jax.tree_util.tree_leaves(
            self._shapes, is_leaf=lambda x: isinstance(x, tuple)))
        self.num_parameters = n_params
        log_dist(f"Model parameters: {n_params / 1e6:.2f}M", ranks=[0])

    def _configure_optimizer(self):
        """Reference ``engine.py:1157`` _configure_optimizer: client optimizer wins,
        else build from config; then "wrap" = attach sharded state specs (or hand
        masters+state to the host/NVMe offload manager, the ZeRO-Offload path)."""
        self._onebit_active = False
        if self.client_optimizer is not None:
            self.optimizer = self.client_optimizer
        else:
            opt_cfg = self._config.optimizer
            self.optimizer = get_optimizer(opt_cfg.type or "adamw", opt_cfg.params)

        # weight decay mask: no decay on 1-D params (biases, norms) — the grouping
        # the reference expresses via param_groups.
        self._wd_mask = jax.tree_util.tree_map(lambda s: len(s) > 1, self._shapes,
                                               is_leaf=lambda x: isinstance(x, tuple))

        offload_cfg = self._config.zero_optimization.offload_optimizer
        self._offloaded = None
        if offload_cfg.device.value != "none":
            if _ABSTRACT_INIT:
                raise ConfigError(
                    "abstract_init does not support optimizer offload (host "
                    "masters are materialized at construction)")
            from .offload import OffloadedOptimizer

            self._offloaded = OffloadedOptimizer(
                self.optimizer, self.params, self._wd_mask,
                compute_dtype=self.compute_dtype,
                param_shardings=self.param_shardings,
                device=offload_cfg.device.value,
                nvme_path=offload_cfg.nvme_path,
                clip=self._config.gradient_clipping,
            )
            # device keeps compute-dtype params only; fp32 masters live on host
            self.params = self._offloaded._device_params()
            self.optimizer_state = None
            log_dist(
                f"Optimizer offload to {offload_cfg.device.value}: device params "
                f"in {self._config.mixed_precision_dtype}, masters on host",
                ranks=[0],
            )
            return

        # -- 1-bit (compressed-momentum) engine path --------------------------------
        from ..ops.onebit import OnebitAdam as _OnebitBase

        self._onebit_active = False
        if isinstance(self.optimizer, _OnebitBase):
            pure_dp = (self.mp_world_size == 1 and self.pipe_stages == 1
                       and self.seq_parallel_size == 1
                       and self.mesh.shape.get(EXPERT_AXIS, 1) == 1)
            dp = self.mesh.shape[DATA_AXIS]
            self._onebit_active = (pure_dp and dp > 1 and self.zero_stage <= 1
                                   and not self.fp16_enabled)
            if self._onebit_active:
                log_dist(
                    f"1-bit optimizer: compressed momentum engages after "
                    f"freeze_step={self.optimizer.freeze_step} "
                    f"(train_batch path, dp={dp})", ranks=[0])
            else:
                logger.warning(
                    "1-bit optimizer: compression requires a pure data-parallel "
                    "mesh, ZeRO<=1, bf16/fp32; running with exact numerics "
                    "(the reference's compression-off behavior)")

        state_shape = jax.eval_shape(self.optimizer.init, self.params)
        if self._onebit_active:
            # worker/server error feedback is per-device state; keep the
            # optimizer moments replicated so every device applies the same
            # reduced-momentum update
            opt_state_specs = jax.tree_util.tree_map(lambda _: P(), state_shape)
        else:
            opt_state_specs = self._opt_state_specs(state_shape)
        self._opt_shardings = named(self.mesh, opt_state_specs)
        if _ABSTRACT_INIT:
            self.optimizer_state = _abstract_tree(state_shape, self._opt_shardings)
        else:
            with self.mesh:
                self.optimizer_state = jax.jit(
                    self.optimizer.init, out_shardings=self._opt_shardings
                )(self.params)
        if self._onebit_active and _ABSTRACT_INIT:
            raise ConfigError(
                "abstract_init does not support 1-bit optimizers (their "
                "error-feedback buffers are materialized at construction)")
        if self._onebit_active:
            if self._config.health.enabled:
                logger.warning(
                    "health.enabled has no effect on the 1-bit optimizer "
                    "step path (no in-graph health side output, no "
                    "skip_step/detectors); the flight recorder stays empty")
            dp = self.mesh.shape[DATA_AXIS]
            L = self.num_parameters
            self._onebit_lpad = -(-L // dp) * dp
            data_sh = NamedSharding(self.mesh, P(DATA_AXIS))
            self._onebit_we = jax.device_put(
                np.zeros(dp * self._onebit_lpad, np.float32), data_sh)
            self._onebit_se = jax.device_put(
                np.zeros(self._onebit_lpad, np.float32), data_sh)
            self._onebit_fns = {}

    def _opt_state_specs(self, state_shape):
        """Param-shaped leaves get ZeRO-1+ data-sharded specs; scalars replicate."""
        sharded_specs = state_partition_specs(
            self._axes, self._shapes, self.mesh,
            zero_stage=self.zero_stage if self.zero_stage >= 1 else 0,
            min_data_shard_elems=self._persist_threshold if self.zero_stage >= 1 else 2 ** 62,
        )

        def spec_for(path, leaf):
            if leaf.ndim == 0:
                return P()
            # state leaves live under a head key ("exp_avg", ...) followed by the
            # param path; strip the head and look up the param's sharded spec.
            sub = tuple(path[1:])
            node = sharded_specs
            try:
                for k in sub:
                    node = node[k.key if hasattr(k, "key") else k]
                if isinstance(node, P):
                    return node
            except (KeyError, TypeError):
                pass
            return P()

        paths, treedef = jax.tree_util.tree_flatten_with_path(state_shape)
        specs = [spec_for(path, leaf) for path, leaf in paths]
        return jax.tree_util.tree_unflatten(treedef, specs)

    # ------------------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------------------
    def _use_pm_1f1b(self, warn=False):
        """1F1B for user PipelineModule layer lists (pipe-only meshes; TP/SP
        widen the manual region in ways the generic switch-vjp schedule does
        not support — those fall back to the module's GPipe loss)."""
        from ..parallel.pipeline_module import PipelineModule

        if not (self.pipe_stages > 1
                and self._config.pipeline.schedule == "1f1b"
                and isinstance(self.module, PipelineModule)):
            return False
        if self.mp_world_size > 1 or self.seq_parallel_size > 1:
            if warn:
                logger.warning(
                    "PipelineModule schedule '1f1b' supports pipe x data "
                    "meshes only (model=%d seq=%d); falling back to gpipe",
                    self.mp_world_size, self.seq_parallel_size)
            return False
        return True

    def _use_1f1b(self, warn=False):
        """Single source of truth for 1F1B eligibility (used by the fwd_bwd
        builder AND the fused-step gate — they must never disagree)."""
        use_1f1b = (self.pipe_stages > 1
                    and self._config.pipeline.schedule == "1f1b"
                    and isinstance(self.params, dict) and "blocks" in self.params
                    # the 1F1B head is autoregressive (label shift + ln_f);
                    # encoder objectives and no-final-norm models take GPipe
                    and getattr(self.module.config, "causal", True)
                    and getattr(self.module.config, "final_layernorm", True))
        if use_1f1b and self.seq_parallel_size > 1:
            if warn:
                logger.warning(
                    "pipeline schedule '1f1b' does not compose with sequence "
                    "parallelism (mesh seq=%d); falling back to gpipe — a "
                    "measured wontfix: root cause and activation-cost numbers "
                    "in PARITY.md 'Known gaps'", self.seq_parallel_size)
            use_1f1b = False
        if use_1f1b and self.mp_world_size > 1 and \
                getattr(self.module.config, "n_experts", 0) > 0:
            # the manual-TP block has no MoE dispatch path
            if warn:
                logger.warning(
                    "pipeline schedule '1f1b' with tensor parallelism does not "
                    "support MoE layers; falling back to gpipe")
            use_1f1b = False
        return use_1f1b

    def _compress(self, params):
        """Apply the current compression phase's masks/fake-quant inside a
        compiled step (no-op without compression_training). The phase's step
        is a BUILD-time constant: schedule transitions invalidate the compiled
        programs (bounded recompiles — one per bit level / phase start)."""
        if self._compression is None:
            return params
        return self._compression.compress_params(params, self._compression_step)

    def _maybe_refresh_compression(self):
        if self._compression is None:
            return
        rt = self._compression
        cfg = rt.config
        step = self.global_steps
        key = (rt.bits_at(step), rt.prune_ratio_at(step),
               cfg.head_pruning.enabled and step >= cfg.head_pruning.schedule_offset,
               cfg.row_pruning.enabled and step >= cfg.row_pruning.schedule_offset)
        if key != self._compression_phase:
            self._compression_phase = key
            self._compression_step = step
            self._train_step_fn = None
            self._fwd_bwd_fn = None
            self._eval_fn = None   # eval must see the same compressed net

    def _wrap_1f1b_step(self, raw_step):
        """Engine-level concerns the manual-vjp schedules don't see:
        compression (compress once outside the schedule, pull the grads back
        through its vjp — the fused step's exact pattern) and eval mode
        (deterministic = no dropout rng, the generic fwd_bwd's trace-time
        convention; mode flips rebuild the program)."""
        def step(params, batch, scale, rng):
            if not self._train_mode:
                rng = None
            if self._compression is None:
                return raw_step(params, batch, scale, rng)
            cp, pullback = jax.vjp(self._compress, params)
            loss, grads = raw_step(cp, batch, scale, rng)
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), grads, cp)
            (grads,) = pullback(grads)
            return loss, grads

        return step

    def _build_fwd_bwd(self):
        gas = self.gradient_accumulation_steps_

        if self._grad_wire_dtype is not None and (
                self._use_1f1b() or self._use_pm_1f1b()):
            logger.warning(
                "grad_reduce_dtype=bf16 does not apply to 1F1B schedules "
                "(their grads cross manual boundaries in fp32 by design); "
                "reducing in fp32")

        if self._use_pm_1f1b(warn=True):
            # 1F1B over a user PipelineModule layer list: the module builds
            # the schedule (switch-vjp per tick); same fwd_bwd contract
            step = self._wrap_1f1b_step(self.module.build_1f1b_step(
                self.mesh, self._pipe_microbatches))
            with self.mesh:
                self._fwd_bwd_fn = jax.jit(
                    step,
                    out_shardings=(NamedSharding(self.mesh, P()),
                                   self._grad_shardings))
            return

        if self._use_1f1b(warn=True):
            # 1F1B: the whole microbatch window (fwd AND bwd, interleaved) is one
            # compiled schedule — in-flight activations bounded by stages, not
            # microbatches (reference runtime/pipe/schedule.py:189 TrainSchedule).
            from ..parallel.pipeline_1f1b import build_1f1b_train_step

            step = self._wrap_1f1b_step(build_1f1b_train_step(
                self.module, self.mesh, self._pipe_microbatches,
                blocks_param_specs=self.param_specs.get("blocks")
                if isinstance(self.param_specs, dict) else None))
            with self.mesh:
                self._fwd_bwd_fn = jax.jit(
                    step,
                    out_shardings=(NamedSharding(self.mesh, P()),
                                   self._grad_shardings),
                )
            return

        def fwd_bwd(params, batch, scale, rng):
            def scaled_loss(p):
                loss = self.module.loss(self._compress(p), batch,
                                        deterministic=not self._train_mode,
                                        dropout_rng=rng)
                # reference scales by 1/gas at backward (engine.py:1793) and by the
                # fp16 loss scale inside the scaler
                return loss * scale.astype(loss.dtype) / gas, loss

            (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
            if self._grad_wire_dtype is not None:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(self._grad_wire_dtype), grads)
            return loss, grads

        with self.mesh:
            self._fwd_bwd_fn = jax.jit(
                fwd_bwd, out_shardings=(NamedSharding(self.mesh, P()), self._grad_shardings)
            )

    def _build_accumulate(self):
        def accumulate(acc, grads):
            return jax.tree_util.tree_map(jnp.add, acc, grads)

        with self.mesh:
            self._accumulate_fn = jax.jit(
                accumulate, donate_argnums=(0,), out_shardings=self._grad_shardings
            )

    def _apply_body(self, params, opt_state, acc_grads, scale, good_steps, lr):
        """Unscale -> overflow check -> clip -> optimizer update -> loss-scale
        update. Shared by the standalone apply program and the fused train step.

        Also computes the per-param-group health side output (tiny f32[G]
        vectors — see ``telemetry/health.py``) and, when the health config's
        nonfinite detector is armed with ``skip_step``, generalizes the fp16
        overflow-skip to any-dtype non-finite grads. The returned flag is the
        *skip* decision (== overflow for plain fp16)."""
        from ..telemetry.health import group_health_stats

        clip = self._config.gradient_clipping
        fp16 = self.fp16_enabled
        window = self._config.fp16.loss_scale_window
        min_scale = self._config.fp16.min_loss_scale
        dynamic = (self._scaler_meta or {}).get("_dynamic", False)

        inv = (1.0 / scale).astype(jnp.float32)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) * inv, acc_grads)
        raw_grads = grads  # pre-clip: the health stats price true magnitudes
        overflow = check_overflow(grads) if fp16 else jnp.asarray(False)
        norm = global_grad_norm(grads)
        if clip > 0:
            grads, _ = clip_grads_by_global_norm(grads, clip, norm=norm)
        new_params, new_state = self.optimizer.update(
            grads, opt_state, params, lr=lr, wd_mask=self._wd_mask
        )
        skip = overflow
        if self._health_skip and not fp16:
            skip = check_overflow(grads)
        if fp16 or self._health_skip:
            # skip the update on overflow (reference FP16_Optimizer.step) /
            # on non-finite grads when the health skip is armed
            new_params = jax.tree_util.tree_map(
                lambda old, new: jnp.where(skip, old, new), params, new_params
            )
            new_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(skip, old, new), opt_state, new_state
            )
        if fp16 and dynamic:
            scale, good_steps = update_scale(
                scale, good_steps, overflow, loss_scale_window=window,
                min_scale=min_scale,
            )
        health = group_health_stats(raw_grads, params, new_params,
                                    self._health_groups)
        return new_params, new_state, scale, good_steps, skip, norm, health

    def _build_apply(self):
        def apply_step(params, opt_state, acc_grads, scale, good_steps, lr):
            return self._apply_body(params, opt_state, acc_grads, scale,
                                    good_steps, lr)

        # Donate params + opt state (NOT grads: arg 2 has the same
        # shapes/dtypes as the params but there are only len(outputs) buffers
        # to alias — new_params + new_state — so donating them too makes XLA
        # report one whole param-tree of "donated buffers were not usable";
        # the grads buffer is freed after the step either way, the engine
        # drops its reference). scale/good_steps are engine-owned and have
        # matching outputs, so they donate too (sanitizer donation rule).
        from ..telemetry.health import HEALTH_STAT_KEYS

        rep = NamedSharding(self.mesh, P())
        with self.mesh:
            self._apply_fn = jax.jit(
                apply_step,
                donate_argnums=(0, 1, 3, 4),
                out_shardings=(
                    self.param_shardings,
                    self._opt_shardings,
                    rep, rep, rep, rep,
                    {k: rep for k in HEALTH_STAT_KEYS},
                ),
            )

    def _build_train_step(self):
        """The whole optimizer step as ONE compiled program: grad-accum loop
        (lax.scan over stacked micro-batches), in-program rng split, optimizer
        apply — params/opt-state donated through. The reference pays a Python
        round-trip per micro-batch plus one per step (``engine.py:1634/:1775/:1971``);
        here ``train_batch`` is a single device dispatch, which also removes the
        grads' HBM round-trip between the backward and the update."""
        gas = self.gradient_accumulation_steps_

        pld_enabled = self._pld is not None

        def train_step(params, opt_state, batches, scale, good_steps, rng, lr,
                       pld_theta):
            new_rng, step_rng = jax.random.split(rng)

            def scaled_loss(p, batch, r):
                loss = self.module.loss(
                    p, batch, deterministic=not self._train_mode,
                    dropout_rng=r,
                    **({"pld_theta": pld_theta} if pld_enabled else {}))
                return loss * scale.astype(loss.dtype) / gas, loss

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)
            grad_wire = self._grad_wire_dtype

            def constrain(g):
                # ZeRO-2: grads sharded over data; with grad_reduce_dtype=
                # bf16 the cast lands BEFORE the constraint, so the reduce
                # collective's payload (and the accumulation carry) is 16-bit
                if grad_wire is not None:
                    g = jax.tree_util.tree_map(
                        lambda a: a.astype(grad_wire), g)
                return jax.lax.with_sharding_constraint(
                    g, self._grad_shardings)
            # compression runs ONCE per step, outside the accumulation scan:
            # cp is the compressed tree the micro-batches differentiate
            # against, and the vjp pulls the accumulated grads back through
            # the masks/STE exactly (identity for fake-quant, mask multiply
            # for pruning) — not gas redundant fake-quant/sort passes
            if self._compression is not None:
                cp, compress_vjp = jax.vjp(self._compress, params)
            else:
                cp, compress_vjp = params, None
            if gas == 1:
                (_, loss), grads = grad_fn(cp, batches, step_rng)
                mean_loss = loss
            else:
                micro_rngs = jax.random.split(step_rng, gas)
                zeros = constrain(jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, p.dtype), params))

                def body(acc, xs):
                    micro, r = xs
                    (_, loss), g = grad_fn(cp, micro, r)
                    acc = constrain(jax.tree_util.tree_map(jnp.add, acc, g))
                    return acc, loss

                grads, losses = jax.lax.scan(body, zeros, (batches, micro_rngs))
                mean_loss = jnp.mean(losses)
            if compress_vjp is not None:
                # the pullback wants cotangents in the primal output dtype
                # (fp32 params); harmless identity cast when grad_wire is off
                grads = jax.tree_util.tree_map(
                    lambda g, p: g.astype(p.dtype), grads, cp)
                (grads,) = compress_vjp(grads)
            grads = constrain(grads)

            (new_params, new_state, scale, good_steps,
             overflow, norm, health) = self._apply_body(params, opt_state,
                                                        grads, scale,
                                                        good_steps, lr)
            return (new_params, new_state, scale, good_steps, overflow, norm,
                    mean_loss, new_rng, health)

        from ..telemetry.health import HEALTH_STAT_KEYS

        rep = NamedSharding(self.mesh, P())
        # Donate the engine-owned step state threaded through the program:
        # params, opt state, AND the loss-scale/good-steps/rng scalars (each
        # has a same-shape output to alias; the engine overwrites its
        # references right after the call, so the stale inputs are dead
        # either way — found by the program sanitizer's donation rule). lr
        # and the batch are caller-owned and have no matching output.
        with self.mesh:
            self._train_step_fn = jax.jit(
                train_step,
                donate_argnums=(0, 1, 3, 4, 5),
                out_shardings=(self.param_shardings, self._opt_shardings,
                               rep, rep, rep, rep, rep, rep,
                               {k: rep for k in HEALTH_STAT_KEYS}),
            )

    def _can_fuse_train_step(self):
        """One-dispatch train_batch: anything but the offloaded (host-step) path
        and the 1F1B schedules (whose fwd+bwd programs have their own contract)."""
        return self._offloaded is None and not self._use_1f1b() \
            and not self._use_pm_1f1b()

    def _fused_train_batch(self, micros):
        if self._train_step_fn is None:
            self._build_train_step()
        gas = self.gradient_accumulation_steps_
        if gas == 1:
            batches = self._shard_batch(micros[0])
        else:
            data_size = self.mesh.shape[DATA_AXIS]
            stacked = {}
            keys = micros[0].keys()
            for k in keys:
                stacked[k] = np.stack([np.asarray(m[k]) for m in micros])
                if stacked[k].ndim >= 2 and stacked[k].shape[1] % data_size:
                    raise ConfigError(
                        f"Batch leaf '{k}' has {stacked[k].shape[1]} rows, not "
                        f"divisible by the data-parallel mesh axis ({data_size}); "
                        f"global micro-batch must be a multiple of dp size")
            shapes = {k: tuple(v.shape[1:]) for k, v in stacked.items()}
            specs = batch_partition_specs(shapes, self.mesh)
            shardings = {
                k: NamedSharding(self.mesh, P(None, *specs[k]))
                for k in keys
            }
            batches = {k: jax.device_put(jnp.asarray(stacked[k]), shardings[k])
                       for k in keys}
        lr = self._current_lr()
        pld_theta = jnp.asarray(
            self._pld.update_state(self.global_steps) if self._pld else 1.0,
            jnp.float32)
        self._last_batch_struct = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding), batches)
        if self.health is not None and self.health.enabled:
            # the record must pin the key that SEEDS this step (the step fn
            # donates + replaces self._rng); host copy before the dispatch
            self._health_rng = np.asarray(self._rng).tolist()
        (self.params, self.optimizer_state, self._scale, self._good_steps,
         skip, grad_norm, mean_loss, self._rng, health) = self._train_step_fn(
            self.params, self.optimizer_state, batches, self._scale,
            self._good_steps, self._rng, jnp.asarray(lr, jnp.float32),
            pld_theta,
        )
        self.micro_steps += gas
        self.global_steps += 1
        skipped = (self.fp16_enabled or self._health_skip) and bool(skip)
        if skipped:
            self.skipped_steps += 1
            log_dist(
                f"step {self.global_steps}: "
                + ("fp16 overflow" if self.fp16_enabled
                   else "non-finite grads (health skip_step)")
                + f", skipping update (loss scale -> {float(self._scale)})",
                ranks=[0],
            )
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._observe_health(health, loss=mean_loss, grad_norm=grad_norm,
                             skipped=skipped, lr=lr, batch=micros)
        if self.global_steps % self._config.steps_per_print == 0:
            events = [("Train/lr", lr, self.global_steps),
                      ("Train/grad_norm", float(grad_norm), self.global_steps),
                      ("Train/loss", float(mean_loss), self.global_steps),
                      ("Train/loss_scale", float(self._scale),
                       self.global_steps),
                      ("Train/skipped_steps", float(self.skipped_steps),
                       self.global_steps)]
            if self._config.comms_logger.enabled:
                ws = self.collective_wire_stats()
                if ws:
                    for kind, s in ws["collectives"].items():
                        if s["count"]:
                            events.append((f"Comm/{kind.replace('-', '_')}_gb",
                                           s["wire_bytes"] / 1e9,
                                           self.global_steps))
                    events.append(("Comm/total_wire_gb",
                                   ws["total_wire_bytes"] / 1e9,
                                   self.global_steps))
                    sched = ws.get("schedule")
                    if sched:
                        # the exposed-vs-overlappable split of the same wire
                        # bytes (schedule audit): trace_summary.py flags
                        # steps whose exposed share exceeds budget
                        events.append(("Comm/exposed_wire_gb",
                                       sched["exposed_bytes"] / 1e9,
                                       self.global_steps))
                        events.append(("Comm/exposed_frac",
                                       sched["exposed_fraction"],
                                       self.global_steps))
            self.monitor.write_events(events)
            self._report_progress()
            self.tracer.flush()
            if self._config.memory_breakdown:
                # reference see_memory_usage role, via the accelerator seam
                from ..accelerator import get_accelerator

                a = get_accelerator()
                log_dist(
                    f"memory: {a.memory_allocated() / 2**30:.2f} GiB in use / "
                    f"{a.total_memory() / 2**30:.2f} GiB", ranks=[0])
        return mean_loss

    def _observe_health(self, stats, loss=None, grad_norm=None, skipped=False,
                        lr=None, batch=None):
        """Feed one step's in-graph health side output to the flight
        recorder (no-op unless ``health.enabled``; the host conversion is
        the one sync the health path pays). Raises ``HealthHalted`` when a
        halt-action detector fires — after its black-box dump published."""
        hm = self.health
        if hm is None or not hm.enabled or stats is None:
            return None
        if self.global_steps % self._config.health.check_interval:
            return None
        from ..telemetry.health import (HealthHalted, batch_fingerprint,
                                        record_from_stats)

        rec = record_from_stats(
            self.global_steps, self._health_groups, stats,
            loss=None if loss is None else float(loss),
            loss_scale=float(self._scale), skipped=bool(skipped),
            grad_norm=None if grad_norm is None else float(grad_norm),
            lr=None if lr is None else float(lr),
            rng=self._health_rng,
            fingerprint=batch_fingerprint(batch))
        anomalies = hm.observe(rec)
        halt = [a for a in anomalies if a.action == "halt"]
        if halt:
            raise HealthHalted(
                f"health detector halt at step {self.global_steps}: "
                + "; ".join(a.message for a in halt))
        return anomalies

    def _apply_curriculum(self, batch):
        """Truncate sequence-dim leaves to the scheduled difficulty (seqlen
        curriculum, reference ``engine.py:1675``). Each distinct difficulty
        value compiles once — schedules quantize via ``difficulty_step``."""
        if self._curriculum is None:
            return batch
        diff = int(self._curriculum.update_difficulty(self.global_steps + 1))
        out = {}
        for k, v in batch.items():
            a = np.asarray(v)
            out[k] = a[:, :diff] if a.ndim >= 2 and a.shape[1] > diff else a
        return out

    @property
    def curriculum_difficulty(self):
        if self._curriculum is None:
            return None
        return self._curriculum.state["current_difficulty"]

    def _build_onebit_step(self, stage, batch_tree):
        """One compiled program per 1-bit stage (reference ``onebit/adam.py``
        warmup vs compressed): everything — local grads, grad accumulation,
        the compressed momentum allreduce, and the update — runs inside ONE
        shard_map over ``data``. The stage is picked HOST-side from
        global_steps (freeze_step is static), so no collective sits inside a
        conditional."""
        from jax.flatten_util import ravel_pytree

        from ..comm.compressed import compressed_allreduce_local

        gas = self.gradient_accumulation_steps_
        opt = self.optimizer
        L_pad = self._onebit_lpad
        bits = self._config.gradient_compression.bits \
            if self._config.gradient_compression.enabled else 1

        def local_grads(params, batches, rng):
            def gfn(p, micro, r):
                loss = self.module.loss(p, micro,
                                        deterministic=not self._train_mode,
                                        dropout_rng=r)
                return loss

            grad_fn = jax.value_and_grad(gfn)
            if gas == 1:
                loss, g = grad_fn(params, batches, rng)
            else:
                rngs = jax.random.split(rng, gas)
                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, p.dtype), params)

                def body(carry, xs):
                    acc, lsum = carry
                    micro, r = xs
                    l, g = grad_fn(params, micro, r)
                    return (jax.tree_util.tree_map(jnp.add, acc, g),
                            lsum + l), None

                (g, lsum), _ = jax.lax.scan(
                    body, (zeros, jnp.zeros((), jnp.float32)), (batches, rngs))
                g = jax.tree_util.tree_map(lambda a: a / gas, g)
                loss = lsum / gas
            return loss, g

        clip = self._config.gradient_clipping

        def body(params, state, we, se, batches, rng, lr):
            loss, g = local_grads(params, batches, rng)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            if stage == "warmup":
                g = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a.astype(jnp.float32), DATA_AXIS), g)
                if clip > 0:  # exact global-norm clip, matching the adamw path
                    g, _ = clip_grads_by_global_norm(g, clip)
                new_params, new_state = opt.update(
                    g, state, params, lr=lr, wd_mask=self._wd_mask)
                return new_params, new_state, we, se, loss
            g = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), g)
            if clip > 0:
                # compressed stage: the exact global-grad norm would need the
                # uncompressed pmean (defeating the compression), so clip each
                # local grad by sqrt(pmean ||g_local||^2) — an upper bound on
                # the mean-grad norm, so spikes are still bounded
                sq = sum(jnp.sum(jnp.square(a))
                         for a in jax.tree_util.tree_leaves(g))
                norm = jnp.sqrt(jax.lax.pmean(sq, DATA_AXIS))
                factor = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
                g = jax.tree_util.tree_map(lambda a: a * factor, g)
            m_tree = opt.local_momentum(g, state)
            flat, unravel = ravel_pytree(m_tree)
            flat = jnp.pad(flat, (0, L_pad - flat.size))
            m_red, we, se = compressed_allreduce_local(
                flat, we, se, DATA_AXIS, bits=bits)
            new_params, new_state = opt.apply_compressed(
                unravel(m_red[:self.num_parameters]), state, params,
                lr=lr, wd_mask=self._wd_mask)
            return new_params, new_state, we, se, loss

        batch_in_specs = jax.tree_util.tree_map(
            lambda a: P(None, DATA_AXIS) if gas > 1 else P(DATA_AXIS),
            batch_tree)
        param_specs = jax.tree_util.tree_map(lambda _: P(), self.params)
        state_specs = jax.tree_util.tree_map(lambda _: P(), self.optimizer_state)
        sm = jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(param_specs, state_specs, P(DATA_AXIS), P(DATA_AXIS),
                      batch_in_specs, P(), P()),
            out_specs=(param_specs, state_specs, P(DATA_AXIS), P(DATA_AXIS),
                       P()),
            axis_names={DATA_AXIS}, check_vma=False)
        with self.mesh:
            return jax.jit(sm, donate_argnums=(0, 1, 2, 3))

    def _onebit_train_batch(self, micros):
        gas = self.gradient_accumulation_steps_
        dp = self.mesh.shape[DATA_AXIS]
        if gas == 1:
            batches = {k: jnp.asarray(np.asarray(micros[0][k]))
                       for k in micros[0]}
        else:
            batches = {k: jnp.asarray(np.stack(
                [np.asarray(m[k]) for m in micros])) for k in micros[0]}
        rows_axis = 1 if gas > 1 else 0
        for k, v in batches.items():
            if v.shape[rows_axis] % dp:
                raise ConfigError(
                    f"Batch leaf '{k}' has {v.shape[rows_axis]} rows, not "
                    f"divisible by the data-parallel mesh axis ({dp})")
        stage = "warmup" if self.optimizer.wants_exact_step(self.global_steps) \
            else "compressed"
        key = (stage, jax.tree_util.tree_structure(batches),
               tuple(tuple(v.shape) for v in batches.values()))
        if key not in self._onebit_fns:
            self._onebit_fns[key] = self._build_onebit_step(stage, batches)
        self._rng, step_rng = jax.random.split(self._rng)
        lr = self._current_lr()
        (self.params, self.optimizer_state, self._onebit_we, self._onebit_se,
         loss) = self._onebit_fns[key](
            self.params, self.optimizer_state, self._onebit_we,
            self._onebit_se, batches, step_rng, jnp.asarray(lr, jnp.float32))
        self.micro_steps += gas
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.global_steps % self._config.steps_per_print == 0:
            self.monitor.write_events(
                [("Train/lr", lr, self.global_steps),
                 ("Train/loss", float(loss), self.global_steps)])
            self._report_progress()
        return loss

    # ------------------------------------------------------------------------------
    # data placement
    # ------------------------------------------------------------------------------
    def _shard_batch(self, batch):
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        data_size = self.mesh.shape[DATA_AXIS]
        for k, v in batch.items():
            if v.ndim >= 1 and v.shape[0] % data_size:
                raise ConfigError(
                    f"Batch leaf '{k}' has {v.shape[0]} rows, not divisible by the "
                    f"data-parallel mesh axis ({data_size}); global micro-batch must "
                    f"be a multiple of dp size"
                )
        shapes = {k: tuple(v.shape) for k, v in batch.items()}
        specs = batch_partition_specs(shapes, self.mesh)
        shardings = named(self.mesh, specs)
        return {k: jax.device_put(batch[k], shardings[k]) for k in batch}

    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None):
        """Reference ``engine.py:1542`` deepspeed_io."""
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or self.micro_batch_size * self._pipe_microbatches
            * self.dp_world_size // max(dist.get_world_size(), 1),
            shuffle=True,
            seed=self._config.seed,
            collate_fn=collate_fn,
            rank=dist.get_rank(),
            num_shards=dist.get_world_size(),
        )

    # ------------------------------------------------------------------------------
    # train API (reference engine.forward :1634 / backward :1775 / step :1971)
    # ------------------------------------------------------------------------------
    def __call__(self, batch):
        return self.forward(batch)

    def forward(self, batch):
        """Compute loss AND gradients for one micro-batch (cached for backward).

        The reference runs a separate autograd backward; under XLA forward and
        backward are one fused program — ``forward`` returns the loss and stashes
        the grads, ``backward`` accumulates them. Numerically identical, one less
        pass over the activations.
        """
        with self.tracer.span("fwd", cat="train", sync=self._telemetry_sync,
                              step=self.global_steps + 1) as sp:
            if self._wall_clock_breakdown:
                self.timers(FORWARD_GLOBAL_TIMER).start()
            self._maybe_refresh_compression()
            if self._fwd_bwd_fn is None:
                self._build_fwd_bwd()
            batch = self._shard_batch(self._apply_curriculum(batch))
            if (self.health is not None and self.health.enabled
                    and self.is_gradient_accumulation_boundary()):
                # first micro-batch of the window: this key deterministically
                # seeds every micro-step split the window consumes
                self._health_rng = np.asarray(self._rng).tolist()
            self._rng, step_rng = jax.random.split(self._rng)
            loss, grads = self._fwd_bwd_fn(self.params, batch, self._scale, step_rng)
            self._cached = (loss, grads)
            self._last_loss = loss
            sp.fence(self._cached)
            if self._wall_clock_breakdown:
                self.timers(FORWARD_GLOBAL_TIMER).stop()
            return loss

    def backward(self, loss=None):
        """Accumulate the cached micro-batch grads (reference engine.backward)."""
        if self._cached is None:
            raise RuntimeError("backward() called before forward()")
        with self.tracer.span("bwd", cat="train", sync=self._telemetry_sync,
                              step=self.global_steps + 1) as sp:
            if self._wall_clock_breakdown:
                self.timers(BACKWARD_GLOBAL_TIMER).start()
            _, grads = self._cached
            self._cached = None
            if self._acc_grads is None:
                self._acc_grads = grads
            else:
                if self._accumulate_fn is None:
                    self._build_accumulate()
                self._acc_grads = self._accumulate_fn(self._acc_grads, grads)
            sp.fence(self._acc_grads)
            self.micro_steps += 1
            if self._wall_clock_breakdown:
                self.timers(BACKWARD_GLOBAL_TIMER).stop()
            return loss

    def is_gradient_accumulation_boundary(self):
        """Reference ``engine.py:1565``."""
        return self.micro_steps % self.gradient_accumulation_steps_ == 0

    def step(self):
        """Apply the optimizer at the accumulation boundary (reference engine.step)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._acc_grads is None:
            raise RuntimeError("step() called with no accumulated gradients")
        with self.tracer.span("step", cat="train", sync=self._telemetry_sync,
                              step=self.global_steps + 1) as sp:
            if self._wall_clock_breakdown:
                self.timers(STEP_GLOBAL_TIMER).start()
            if self._offloaded is not None:
                return self._offloaded_step()
            if self._apply_fn is None:
                self._build_apply()
            lr = self._current_lr()
            (self.params, self.optimizer_state, self._scale,
             self._good_steps, skip, grad_norm, health) = self._apply_fn(
                self.params, self.optimizer_state, self._acc_grads, self._scale,
                self._good_steps, jnp.asarray(lr, jnp.float32),
            )
            self._acc_grads = None  # donated; re-seeded by the next backward()
            sp.fence(self.params)
            self.global_steps += 1
            skipped = (self.fp16_enabled or self._health_skip) and bool(skip)
            if skipped:
                self.skipped_steps += 1
                log_dist(
                    f"step {self.global_steps}: "
                    + ("fp16 overflow" if self.fp16_enabled
                       else "non-finite grads (health skip_step)")
                    + f", skipping update (loss scale -> {float(self._scale)})",
                    ranks=[0],
                )
            elif self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self._observe_health(health, loss=self._last_loss,
                                 grad_norm=grad_norm, skipped=skipped, lr=lr)
            if self._wall_clock_breakdown:
                self.timers(STEP_GLOBAL_TIMER).stop()
                # monitor events read WITHOUT reset so the log() line below
                # still sees the same window (log resets)
                self.timers.write_events(
                    self.monitor,
                    [FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                     STEP_GLOBAL_TIMER],
                    self.global_steps, reset=False)
                self.timers.log(
                    [FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER]
                )
            if self.global_steps % self._config.steps_per_print == 0:
                self.monitor.write_events(
                    [("Train/lr", lr, self.global_steps),
                     ("Train/grad_norm", float(grad_norm), self.global_steps),
                     ("Train/loss_scale", float(self._scale),
                      self.global_steps),
                     ("Train/skipped_steps", float(self.skipped_steps),
                      self.global_steps)]
                )
                self.tracer.flush()
            return grad_norm

    def _offloaded_step(self):
        """ZeRO-Offload step: grads -> host, host optimizer on fp32 masters,
        compute-dtype params -> device (reference stage_1_and_2.py CPU-offload
        path :1031-1113 + cpu_adam kernels)."""
        from ..ops import update_scale

        lr = self._current_lr()
        scale_inv = 1.0 / float(self._scale)
        # only the health path needs the step's inputs held alive (to price
        # the applied update); otherwise release them on schedule — the
        # offload path exists for tight device memory
        grads = old_params = None
        if self.health is not None and self.health.enabled:
            grads, old_params = self._acc_grads, self.params
        self.params, grad_norm, overflow = self._offloaded.step(
            self._acc_grads, lr, scale_inv)
        self._acc_grads = None
        self.global_steps += 1
        if self.fp16_enabled:
            dynamic = (self._scaler_meta or {}).get("_dynamic", False)
            if dynamic:
                self._scale, self._good_steps = update_scale(
                    self._scale, self._good_steps, jnp.asarray(overflow),
                    loss_scale_window=self._config.fp16.loss_scale_window,
                    min_scale=self._config.fp16.min_loss_scale,
                )
        if overflow:
            self.skipped_steps += 1
            log_dist(
                f"step {self.global_steps}: overflow, skipping update "
                f"(loss scale -> {float(self._scale)})",
                ranks=[0],
            )
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self._wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).stop()
            self.timers.log(
                [FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER]
            )
        if self.health is not None and self.health.enabled:
            # device-side stats for the host-stepped path: one small jitted
            # program over (grads, old, new) — still no callbacks in-step
            if self._health_fn is None:
                from ..telemetry.health import group_health_stats

                groups = self._health_groups
                with self.mesh:
                    self._health_fn = jax.jit(
                        lambda g, old, new, inv: group_health_stats(
                            jax.tree_util.tree_map(
                                lambda a: a.astype(jnp.float32) * inv, g),
                            old, new, groups))
            stats = self._health_fn(grads, old_params, self.params,
                                    jnp.asarray(scale_inv, jnp.float32))
            self._observe_health(stats, loss=self._last_loss,
                                 grad_norm=grad_norm, skipped=bool(overflow),
                                 lr=lr)
        if self.global_steps % self._config.steps_per_print == 0:
            self.monitor.write_events(
                [("Train/lr", lr, self.global_steps),
                 ("Train/grad_norm", float(grad_norm), self.global_steps),
                 ("Train/loss_scale", float(self._scale), self.global_steps),
                 ("Train/skipped_steps", float(self.skipped_steps),
                  self.global_steps)]
            )
        return grad_norm

    def train_batch(self, data_iter=None, batch=None):
        """Full accumulation window in one call (reference PipelineEngine.train_batch
        shape). Feeds ``gradient_accumulation_steps`` micro-batches. On the main
        path this is ONE device dispatch (see ``_build_train_step``); the returned
        loss is a device scalar — not synced — so back-to-back calls pipeline.
        Exception: fp16's dynamic loss scaling must read the overflow flag each
        step (as the reference's ``FP16_Optimizer.step`` does), which syncs;
        the pipelining guarantee holds for bf16/fp32 (and for the health
        monitor's per-step observe when ``health.enabled``, which also syncs).
        """
        try:
            return self._train_batch_impl(data_iter=data_iter, batch=batch)
        except Exception as e:
            # black-box on the way down: an unhandled step exception
            # publishes the ring buffer before propagating. HealthHalted
            # already dumped (the halt action fires dump first).
            from ..telemetry.health import HealthHalted

            if (self.health is not None and self.health.enabled
                    and self._config.health.dump_on_exception
                    and not isinstance(e, HealthHalted)):
                self.health.dump("exception",
                                 extra={"exception": repr(e),
                                        "step": self.global_steps})
            raise

    def _train_batch_impl(self, data_iter=None, batch=None):
        step_no = self.global_steps + 1
        with self.tracer.span("train_batch", cat="train",
                              sync=self._telemetry_sync, step=step_no):
            self.tput_timer.start()
            self._maybe_refresh_compression()
            with self.tracer.span("data", cat="train", step=step_no):
                micros = []
                for _ in range(self.gradient_accumulation_steps_):
                    micro = batch if batch is not None else next(data_iter)
                    micros.append(self._apply_curriculum(micro))
            if self._onebit_active:
                with self.tracer.span("step", cat="train", step=step_no):
                    mean_loss = self._onebit_train_batch(micros)
                self.tput_timer.stop(global_step=True)
                return mean_loss
            if self._can_fuse_train_step():
                # ONE device dispatch: fwd+bwd+apply (and the in-program
                # ZeRO-3 gather schedule) are indistinguishable host-side —
                # the schedule auditor attributes inside the program
                with self.tracer.span("step", cat="train", step=step_no):
                    mean_loss = self._fused_train_batch(micros)
                self.tput_timer.stop(global_step=True)
                return mean_loss
            losses = []
            for micro in micros:
                loss = self.forward(micro)
                self.backward(loss)
                losses.append(loss)
            self.step()
            self.tput_timer.stop(global_step=True)
            mean_loss = jnp.mean(jnp.stack(losses)) if len(losses) > 1 else losses[0]
            if self.global_steps % self._config.steps_per_print == 0:
                self.monitor.write_events([("Train/loss", float(mean_loss), self.global_steps)])
                self._report_progress()
            return mean_loss

    def eval_batch(self, batch):
        """Loss without grads. On pipe meshes this runs the PIPELINED forward
        with a single microbatch: weights stay stage-local and activations move
        by ppermute, where the previous non-pipelined eval read the pipe-sharded
        layer stack through the auto partitioner — an all-gather of every block
        weight per eval step (brutal at multi-B params). M=1 keeps eval free of
        any microbatch divisibility contract; the (S-1)/S bubble is irrelevant
        at eval rates."""
        self._maybe_refresh_compression()
        if self._eval_fn is None:
            module = self.module
            if self.pipe_stages > 1:
                import dataclasses

                module = type(self.module)(
                    dataclasses.replace(self.module.config,
                                        pipeline_microbatches=1)
                )
            # eval the COMPRESSED net (what redundancy_clean will deploy),
            # not the dense masters
            with self.mesh:
                self._eval_fn = jax.jit(
                    lambda p, b: module.loss(self._compress(p), b))
        return self._eval_fn(self.params, self._shard_batch(batch))

    def _current_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()[0]
        return self.optimizer.lr

    def get_lr(self):
        return [self._current_lr()]

    def set_lr(self, lr):
        """Override the learning rate (reference engine ``set_lr``): updates
        the scheduler's base lr when one is attached, else the optimizer's.
        Takes effect next step — lr is a traced runtime argument, so no
        recompile."""
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "set_lr"):
            self.lr_scheduler.set_lr(lr)
        elif self.lr_scheduler is not None:
            raise ValueError(
                f"{type(self.lr_scheduler).__name__} does not support set_lr; "
                "drive the schedule through its own params")
        else:
            self.optimizer.lr = lr

    def train(self, mode=True):
        """torch-style mode flag (reference engine.train/eval): eval mode makes
        ``forward``/``train_batch`` run deterministically (no dropout/PLD).
        Flipping the mode rebuilds the compiled step programs (the flag is
        baked into the trace)."""
        mode = bool(mode)
        if mode != self._train_mode:
            self._train_mode = mode
            self._fwd_bwd_fn = None
            self._train_step_fn = None
            if getattr(self, "_onebit_active", False):
                self._onebit_fns = {}
        return self

    def eval(self):
        return self.train(False)

    def destroy(self):
        """Release device memory and compiled programs (reference
        engine.py:381 ``destroy``). The engine's jitted closures capture
        ``self``, so dropping the last user reference leaves a cycle that
        holds params/optimizer state in HBM until an eventual full gc pass;
        after ``destroy()`` the buffers are freed immediately. The engine is
        unusable afterwards."""
        self.params = None
        self.optimizer_state = None
        self._acc_grads = None
        self._cached = None   # forward()'s stashed (loss, grads)
        self._fwd_bwd_fn = None
        self._accumulate_fn = None
        self._apply_fn = None
        self._train_step_fn = None
        self._eval_fn = None
        if getattr(self, "_onebit_active", False):
            self._onebit_fns = {}
            self._onebit_we = None   # error-feedback buffers (~params-sized)
            self._onebit_se = None
        self._offloaded = None
        self.tracer.flush()  # don't lose the trace tail with the engine
        import gc

        # no jax.clear_caches(): that is process-global and would force every
        # OTHER live engine in the process to recompile; dropping this
        # engine's jitted wrappers frees its executables
        gc.collect()

    def collective_wire_stats(self, refresh=False):
        """Per-step collective wire bytes of the compiled train step, by
        kind and payload dtype (``profiling/collectives.py``).

        Available after the first fused ``train_batch`` call. The first call
        triggers ONE extra AOT compile of the step program (the audit needs
        a fresh pass-pipeline run to snapshot the post-SPMD-partitioning
        HLO); the result is cached. Returns None when the fused step has not
        run yet (pipeline/offload/1-bit paths are not audited here — use
        ``tools/collective_audit.py`` on a matching config instead).

        Only offered at gradient_accumulation_steps == 1: with gas > 1 the
        accumulation scan and the layer scan are BOTH while bodies, and the
        single loop-trip multiplier would mis-scale them in opposite
        directions (gathers x8 under, reduces x5 over at gas=8/L=40) —
        wrong monitor numbers are worse than none.
        """
        if self._wire_stats is not None and not refresh:
            return self._wire_stats
        if self._train_step_fn is None or self._last_batch_struct is None:
            return None
        if self.gradient_accumulation_steps_ > 1:
            logger.warning(
                "collective_wire_stats: not emitted at gradient_accumulation"
                "_steps=%d — the HLO loop-trip attribution is only exact at "
                "gas=1 (audit a gas=1 config with tools/collective_audit.py "
                "instead)", self.gradient_accumulation_steps_)
            return None
        from ..profiling.collectives import audit_lowered

        # lower() only traces avals — live trees are fine (nothing executes,
        # nothing is donated), the batch rides as ShapeDtypeStructs
        lowered = self._train_step_fn.lower(
            self.params, self.optimizer_state, self._last_batch_struct,
            self._scale, self._good_steps, self._rng,
            jnp.asarray(0.0, jnp.float32), jnp.asarray(1.0, jnp.float32))
        trip = getattr(self.module.config, "n_layers", 1) \
            if getattr(self.module.config, "scan_layers", False) else 1
        from ..profiling.sanitizer import ATTENTION_F32_ALLOW

        dtype = {jnp.bfloat16: "bf16", jnp.float16: "f16"}.get(
            self.compute_dtype, "f32")
        self._wire_stats = audit_lowered(
            lowered, self.dp_world_size * self.mp_world_size
            * self.pipe_stages * self.seq_parallel_size,
            loop_trip_count=trip,
            sanitizer_config={"compute_dtype": dtype,
                              "allow": list(ATTENTION_F32_ALLOW)})
        return self._wire_stats

    def _report_progress(self):
        """Reference ``engine.py:2167`` _report_progress."""
        log_dist(
            f"step={self.global_steps}, skipped={self.skipped_steps}, "
            f"lr={self._current_lr():.3e}, loss_scale={float(self._scale):.1f}",
            ranks=[0],
        )

    # ------------------------------------------------------------------------------
    # config accessors (reference engine.py:641-836 property farm)
    # ------------------------------------------------------------------------------
    @property
    def config(self):
        return self._config

    def train_batch_size(self):
        return self.train_batch_size_

    def train_micro_batch_size_per_gpu(self):
        return self.micro_batch_size

    def gradient_accumulation_steps(self):
        return self.gradient_accumulation_steps_

    def zero_optimization_stage(self):
        return self.zero_stage

    @property
    def loss_scale(self):
        return float(self._scale)

    def get_global_grad_norm(self):
        if self._acc_grads is None:
            return 0.0
        return float(global_grad_norm(self._acc_grads))

    def module_state_dict(self):
        """Reference ``engine.module_state_dict``: the module's weights as a
        host tree (consolidated across shards)."""
        return self.consolidated_16bit_state_dict()

    def consolidated_16bit_state_dict(self):
        """Live consolidated weights in the compute dtype (reference
        ``_zero3_consolidated_16bit_state_dict``, ``engine.py:3127``): gathers
        every (possibly ZeRO-3/TP-sharded) param to host as one numpy tree.
        Rank 0 returns the dict; other processes return None. Small/medium
        models only — a 13B tree will not fit one host; use the sharded
        checkpoint + ``consolidate`` offline tool instead."""
        params = self._offloaded.masters if self._offloaded is not None \
            else self.params
        if dist.get_rank() != 0 and jax.process_count() > 1:
            # participate in any cross-host gathers, drop the result
            jax.tree_util.tree_map(lambda a: np.asarray(jax.device_get(a)),
                                   params)
            return None
        cast = np.dtype(jnp.dtype(self.compute_dtype).name) \
            if self.compute_dtype != jnp.float32 else np.float32
        return jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)).astype(cast), params)

    # ------------------------------------------------------------------------------
    # checkpointing (reference engine.py:2493 load / :2798 save)
    # ------------------------------------------------------------------------------
    def capture_step_state(self, client_state=None):
        """The complete step state as a ``(state_tree, meta)`` pair — the
        single source of truth for what a checkpoint must carry so a resumed
        trajectory is CONTINUOUS: params + optimizer state (the tree), and in
        meta the counters, loss-scale/good-steps, the live rng key (bitwise
        stream continuity across restarts), the lr-scheduler state, and the
        health monitor's ring-buffer window (so spike/z-score detectors don't
        restart blind after a preemption). Also the capture point the elastic
        snapshot path reads every ``snapshot_interval`` steps."""
        if self._offloaded is not None:
            state = {
                "params": self._offloaded.masters,  # fp32 masters, not bf16 copies
                "optimizer_state": self._offloaded.state_for_checkpoint(),
            }
        else:
            state = {
                "params": self.params,
                "optimizer_state": self.optimizer_state,
            }
        meta = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "loss_scale": float(self._scale),
            "good_steps": int(self._good_steps),
            "rng": np.asarray(self._rng).tolist(),
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler else None,
            "zero_stage": self.zero_stage,
            "mesh": dict(self.mesh.shape),
            "client_state": client_state or {},
        }
        if self.health is not None and self.health.enabled:
            meta["health"] = self.health.state_dict()
        return state, meta

    def save_checkpoint(self, save_dir, tag=None, client_state=None):
        tag = tag or f"global_step{self.global_steps}"
        # all ranks must save the same tag/step or shard files interleave
        # (reference engine.py:2781 checkpoint tag validation)
        dist.assert_same_across_ranks(
            {"tag": np.frombuffer(tag.encode(), np.uint8),
             "step": self.global_steps}, name="checkpoint tag")
        state, meta = self.capture_step_state(client_state)
        path = os.path.join(save_dir, tag)
        with self.tracer.span("checkpoint/save", cat="checkpoint", tag=tag,
                              step=self.global_steps):
            with self.tracer.span("checkpoint/write", cat="checkpoint",
                                  step=self.global_steps):
                self.checkpoint_engine.save(state, path, meta=meta)
            with self.tracer.span("checkpoint/commit", cat="checkpoint",
                                  step=self.global_steps):
                self.checkpoint_engine.commit(tag)
        self.tracer.flush()
        log_dist(f"Saved checkpoint {path}", ranks=[0])
        return path

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        verify=True):
        with self.tracer.span("checkpoint/resume", cat="checkpoint",
                              tag=tag) as _resume_span:
            return self._load_checkpoint(load_dir, tag, load_optimizer_states,
                                         verify, _resume_span)

    def _load_checkpoint(self, load_dir, tag, load_optimizer_states, verify,
                         span):
        if tag is None:
            from ..checkpoint import atomic as ckpt_atomic

            tag = ckpt_atomic.read_latest(load_dir)
            if tag is not None and not os.path.isdir(
                    os.path.join(load_dir, tag)):
                # dangling pointer: the tag was quarantined/pruned out from
                # under it (routine after try_resume's recovery walk)
                log_dist(f"checkpoint 'latest' points at missing tag "
                         f"{tag!r} — falling back to newest published tag",
                         ranks=[0])
                tag = None
            if tag is None:
                # newest published tag; stale .tmp stages and quarantined
                # .corrupt dirs are never resume targets
                tags = ckpt_atomic.list_tags(load_dir, newest_first=True)
                if not tags:
                    return None, {}
                tag = tags[0]
        path = os.path.join(load_dir, tag)
        # the marker records the writing mesh — read it up front so a
        # rescaled resume traces as checkpoint/reshard (the region reads
        # through _parse_ranges onto the new mesh's shardings ARE the
        # reshard work), an equal-scale one as checkpoint/load
        from ..checkpoint import atomic as ckpt_atomic

        marker = ckpt_atomic.read_marker(path)
        marker_mesh = marker.get("mesh") if marker else None
        reshard = bool(marker_mesh
                       and dict(marker_mesh) != dict(self.mesh.shape))
        load_span = "checkpoint/reshard" if reshard else "checkpoint/load"
        if self._offloaded is not None:
            template = {"params": self._offloaded.masters,
                        "optimizer_state": self._offloaded.state_for_checkpoint()}
            with self.tracer.span(load_span, cat="checkpoint", tag=tag):
                state, meta = self.checkpoint_engine.load(path,
                                                          template=template,
                                                          shardings=None,
                                                          verify=verify)
            self._offloaded.load_masters(state["params"])
            if load_optimizer_states:
                self._offloaded.load_state(state["optimizer_state"])
            self.params = self._offloaded._device_params()
        else:
            template = {"params": self.params, "optimizer_state": self.optimizer_state}
            shardings = {"params": self.param_shardings,
                         "optimizer_state": self._opt_shardings}
            with self.tracer.span(load_span, cat="checkpoint", tag=tag):
                state, meta = self.checkpoint_engine.load(path,
                                                          template=template,
                                                          shardings=shardings,
                                                          verify=verify)
            self.params = state["params"]
            if load_optimizer_states:
                self.optimizer_state = state["optimizer_state"]
        self.global_steps = meta["global_steps"]
        self.micro_steps = meta["micro_steps"]
        self.skipped_steps = meta["skipped_steps"]
        self._scale = jnp.asarray(meta["loss_scale"], jnp.float32)
        self._good_steps = jnp.asarray(meta["good_steps"], jnp.int32)
        if meta.get("rng") is not None:
            # bitwise stream continuity: the restored trajectory folds the
            # SAME dropout/noise keys the uninterrupted run would have
            self._rng = jnp.asarray(np.asarray(meta["rng"], np.uint32))
        if self.health is not None and self.health.enabled \
                and meta.get("health"):
            # ring-buffer carry: the spike/z-score detectors resume with the
            # pre-preemption window instead of restarting blind
            self.health.load_state_dict(meta["health"])
        if self.lr_scheduler is not None and meta.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        # one source of truth for "rescaled": the marker mesh that chose the
        # span name, falling back to the meta mesh only for marker-less
        # (legacy) tags — the span and the Elastic/resumes_rescaled counter
        # must never contradict each other
        saved_mesh = marker_mesh or meta.get("mesh")
        self._last_resume_rescaled = bool(
            saved_mesh and dict(saved_mesh) != dict(self.mesh.shape))
        if self._last_resume_rescaled:
            log_dist(
                f"Checkpoint {tag} was written on mesh {dict(saved_mesh)} — "
                f"resharded onto {dict(self.mesh.shape)} "
                f"(params + ZeRO optimizer state)", ranks=[0])
        span.set(tag=tag, step=self.global_steps,
                 rescaled=self._last_resume_rescaled)
        log_dist(f"Loaded checkpoint {path} at step {self.global_steps}", ranks=[0])
        return path, meta.get("client_state", {})
