"""Smaller runtime utilities with direct reference counterparts.

- ``Eigenvalue``: power-iteration estimate of a loss-curvature eigenvalue per
  param block (reference ``runtime/eigenvalue.py`` — feeds the compression
  scheduler's layer sensitivity).
- ``ProgressiveLayerDrop``: the PLD theta schedule (reference
  ``runtime/progressive_layer_drop.py``); the keep-probability gate is applied
  by ``stack_apply`` when enabled.
- ``TiledLinear``: a linear whose matmul runs tile-by-tile over the output dim
  (reference ``runtime/zero/tiling.py`` splits huge linears so ZeRO-3 only
  gathers a tile at a time; under XLA the win is bounding live activation
  slices for very wide layers).
"""

import jax
import jax.numpy as jnp


class Eigenvalue:
    """Power iteration on the loss Hessian-vector product, per param leaf.

    ``loss_fn(params) -> scalar``; returns {path: eigenvalue estimate}. HVP is
    forward-over-reverse (jvp of grad) — exact, no finite differences."""

    def __init__(self, max_iter=20, tol=1e-2, seed=0):
        self.max_iter = max_iter
        self.tol = tol
        self.seed = seed

    def compute(self, loss_fn, params):
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        rng = jax.random.PRNGKey(self.seed)
        flat, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(flat))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, flat)])

        def norm(t):
            return jnp.sqrt(sum(jnp.sum(jnp.square(l))
                                for l in jax.tree_util.tree_leaves(t)))

        eig_prev = jnp.asarray(0.0)
        for _ in range(self.max_iter):
            n = norm(v)
            v = jax.tree_util.tree_map(lambda a: a / (n + 1e-30), v)
            hv = hvp(v)
            eig = sum(jnp.sum(a * b) for a, b in zip(
                jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(hv)))
            if abs(float(eig - eig_prev)) <= self.tol * abs(float(eig) + 1e-30):
                v = hv
                eig_prev = eig
                break
            v, eig_prev = hv, eig
        return float(eig_prev)


class ProgressiveLayerDrop:
    """theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar (reference
    ``progressive_layer_drop.py``); per-layer keep prob follows the usual
    depth scaling keep_i = 1 - (i/L) * (1 - theta)."""

    def __init__(self, theta=0.5, gamma=0.001):
        self.theta_bar = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def update_state(self, global_step):
        import math

        self.current_theta = ((1.0 - self.theta_bar)
                              * math.exp(-self.gamma * global_step)
                              + self.theta_bar)
        return self.current_theta

    def get_theta(self):
        return self.current_theta

    def keep_prob(self, layer_idx, n_layers):
        return 1.0 - (layer_idx / max(1, n_layers)) * (1.0 - self.current_theta)


def tiled_linear_apply(p, x, tiles=4, compute_dtype=None):
    """y = x @ W (+ b), computed in ``tiles`` slices of the output dim —
    bounds the live [tokens, out/tiles] slice (reference TiledLinear,
    ``runtime/zero/tiling.py``). Exactly equals the untiled linear."""
    kernel = p["kernel"]
    if compute_dtype is not None:
        kernel = kernel.astype(compute_dtype)
        x = x.astype(compute_dtype)
    out_dim = kernel.shape[-1]
    if out_dim % tiles:
        tiles = 1
    width = out_dim // tiles
    pieces = [x @ jax.lax.slice_in_dim(kernel, t * width, (t + 1) * width, axis=-1)
              for t in range(tiles)]
    y = jnp.concatenate(pieces, axis=-1)
    if "bias" in p:
        b = p["bias"].astype(y.dtype) if compute_dtype is not None else p["bias"]
        y = y + b
    return y
