"""ZeRO-Infinity parameter streaming: train models bigger than device memory.

Reference: ``runtime/swap_tensor/partitioned_param_swapper.py`` +
``partitioned_optimizer_swapper.py`` — ZeRO-Infinity pages fp16 params
NVMe->GPU per module (forward and backward), with the optimizer states swapped
around the CPU update. The eager hook machinery doesn't translate to XLA;
the TPU-native structure is a *chunked training step*:

- params live on HOST (fp32 masters; optionally backed by the NVMe store) —
  the device never holds the full model;
- forward: a python loop over layer chunks; each chunk's params are placed on
  device (read-ahead for chunk i+1 overlaps compute of chunk i via the aio
  pool), one jitted chunk-forward runs, and only the boundary activation is
  kept — device residency is O(chunk + boundaries);
- backward: the reverse loop re-fetches each chunk and runs ``jax.vjp`` of the
  chunk forward (recompute-in-chunk, the same trade the reference makes with
  activation checkpointing at the swap boundary);
- the chunk's gradient goes STRAIGHT into the host optimizer update for those
  layers (the ``OffloadedOptimizer`` per-leaf path) and is dropped — gradients
  are never all resident either.

The embedding/head run on device (they are needed densely by the loss); their
grads flow through ``jax.vjp`` exactly like the 1F1B schedule's embed/head
split (``parallel/pipeline_1f1b.py``).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..models.transformer import _remat_policy, block_apply, _norm_apply
from ..utils.logging import log_dist


class InfinityParamEngine:
    """Chunked-streaming train step for a CausalLM whose stacked blocks exceed
    device memory. Single-chip oriented (the multi-chip path shards params
    instead — ZeRO-3); composes with the host optimizer (ZeRO-Offload)."""

    def __init__(self, model, *, chunk_layers=4, optimizer=None, lr=1e-4,
                 nvme_path="", compute_dtype=jnp.bfloat16, wd_mask=None,
                 seed=0):
        from ..ops.optimizers import Adam

        self.model = model
        self.cfg = model.config
        if self.cfg.n_layers % chunk_layers:
            raise ValueError(f"n_layers {self.cfg.n_layers} must divide "
                             f"chunk_layers {chunk_layers}")
        self.chunk_layers = chunk_layers
        self.n_chunks = self.cfg.n_layers // chunk_layers
        self.compute_dtype = compute_dtype
        self.optimizer = optimizer or Adam(lr=lr)
        self.lr = lr

        cpu = jax.local_devices(backend="cpu")[0]
        self.cpu = cpu
        rng = jax.random.PRNGKey(seed)
        from ..models.layers import split_params_axes

        with jax.default_device(cpu):
            values = split_params_axes(model.init(rng))[0]
        # split: blocks stay host-resident; embed/head live on device
        # np.array(copy=True): np.asarray of a CPU-backed jax array is a
        # read-only zero-copy view; the page-out path writes in place
        self.blocks_host = jax.tree_util.tree_map(
            lambda a: np.array(a, copy=True), values["blocks"])
        self.outer = jax.tree_util.tree_map(
            jnp.asarray, {k: v for k, v in values.items() if k != "blocks"})

        self.opt_state_blocks = {
            "exp_avg": jax.tree_util.tree_map(np.zeros_like, self.blocks_host),
            "exp_avg_sq": jax.tree_util.tree_map(np.zeros_like,
                                                 self.blocks_host),
        }
        self.opt_state_outer = self.optimizer.init(self.outer)
        self.step_count = 0

        self._fns = {}  # seq_len -> (chunk_fwd, chunk_bwd, rope)
        self._chunk_update = None
        n_params = sum(int(np.prod(l.shape)) for l in
                       jax.tree_util.tree_leaves(self.blocks_host))
        log_dist(f"InfinityParamEngine: {self.n_chunks} chunks x "
                 f"{chunk_layers} layers, {n_params/1e6:.1f}M streamed params",
                 ranks=[0])

    # ------------------------------------------------------------------
    def _chunk(self, tree, i):
        lo = i * self.chunk_layers
        return jax.tree_util.tree_map(
            lambda a: a[lo:lo + self.chunk_layers], tree)

    def _fetch_chunk(self, i):
        """Host slice -> device (the NVMe->device page-in; with an NVMe store
        the host slice itself would be read through the aio pool)."""
        return jax.tree_util.tree_map(
            lambda a: jnp.asarray(a[i * self.chunk_layers:
                                    (i + 1) * self.chunk_layers]),
            self.blocks_host)

    def _get_fns(self, seq_len):
        if seq_len in self._fns:
            return self._fns[seq_len]
        cfg = self.cfg
        from ..models import layers as L

        has_rope = cfg.position_embedding == "rope"
        rope_tables = None
        if has_rope:
            pos = jnp.arange(seq_len)[None, :]
            rope_tables = L.rotary_embedding(
                pos, cfg.rotary_dim or cfg.head_dim, cfg.rope_base)
        alibi_const = (L.alibi_bias(cfg.n_heads, seq_len, seq_len)
                       if cfg.position_embedding == "alibi" else None)

        def blk(w, h, rope):
            out, _ = block_apply(cfg, w, h, rope=rope, alibi=alibi_const)
            return out

        if cfg.remat:
            blk = jax.checkpoint(blk, policy=_remat_policy(cfg))

        def chunk_fwd(wchunk, h, rope):
            def body(carry, w_i):
                return blk(w_i, carry, rope), None

            h, _ = jax.lax.scan(body, h, wchunk)
            return h

        def chunk_bwd(wchunk, h_in, rope, g_out):
            out, vjp = jax.vjp(lambda w, hh: chunk_fwd(w, hh, rope),
                               wchunk, h_in)
            gw, gh = vjp(g_out)
            return gw, gh

        fns = (jax.jit(chunk_fwd), jax.jit(chunk_bwd), rope_tables)
        self._fns[seq_len] = fns

        # streamed blocks use the Adam-family update with the CONFIGURED
        # optimizer's hyperparameters (the reference's CPUAdam role); exotic
        # optimizers apply only to the resident embed/head params
        b1 = getattr(self.optimizer, "b1", 0.9)
        b2 = getattr(self.optimizer, "b2", 0.999)
        eps = getattr(self.optimizer, "eps", 1e-8)
        wd = getattr(self.optimizer, "weight_decay", 0.0)

        def chunk_update(wchunk, gw, m, v, lr, step):
            c1 = 1.0 - b1 ** step
            c2 = 1.0 - b2 ** step

            def leaf(p, g, mm, vv):
                g = g.astype(jnp.float32)
                mm = b1 * mm + (1 - b1) * g
                vv = b2 * vv + (1 - b2) * g * g
                upd = (mm / c1) / (jnp.sqrt(vv / c2) + eps)
                if wd:
                    upd = upd + wd * p
                return p - lr * upd, mm, vv

            out = jax.tree_util.tree_map(leaf, wchunk, gw, m, v)
            newp = jax.tree_util.tree_map(lambda t: t[0], out,
                                          is_leaf=lambda t: isinstance(t, tuple))
            newm = jax.tree_util.tree_map(lambda t: t[1], out,
                                          is_leaf=lambda t: isinstance(t, tuple))
            newv = jax.tree_util.tree_map(lambda t: t[2], out,
                                          is_leaf=lambda t: isinstance(t, tuple))
            return newp, newm, newv

        self._chunk_update = jax.jit(chunk_update)
        return fns

    # ------------------------------------------------------------------
    def train_step(self, batch):
        """One full step. Returns the scalar loss. Device residency: one chunk
        of params (+grads transiently) + n_chunks boundary activations."""
        cfg = self.cfg
        model = self.model
        input_ids = jnp.asarray(batch["input_ids"], jnp.int32)
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)],
                axis=1)

        # ---- embedding under vjp
        def embed(outer):
            x = jnp.take(outer["wte"]["weight"].astype(self.compute_dtype),
                         input_ids, axis=0)
            if cfg.position_embedding == "learned":
                s = input_ids.shape[1]
                x = x + outer["wpe"]["weight"].astype(
                    self.compute_dtype)[:s][None]
            return x

        x, embed_vjp = jax.vjp(embed, self.outer)
        chunk_fwd, chunk_bwd, rope = self._get_fns(input_ids.shape[1])

        # ---- forward sweep, keeping chunk INPUT boundaries
        boundaries = []
        w_next = self._fetch_chunk(0)
        for i in range(self.n_chunks):
            w = w_next
            if i + 1 < self.n_chunks:
                w_next = self._fetch_chunk(i + 1)  # page-in next while compute
            boundaries.append(x)
            x = chunk_fwd(w, x, rope)

        # ---- head + loss under vjp
        def head_loss(outer, h):
            hn = _norm_apply(cfg, outer["ln_f"], h)
            return model.head_ce(outer, hn, labels)

        loss, head_vjp = jax.vjp(head_loss, self.outer, x)
        g_outer_head, g = head_vjp(jnp.ones((), loss.dtype))

        # ---- reverse sweep: per-chunk vjp + immediate optimizer update
        self.step_count += 1
        step = jnp.asarray(self.step_count, jnp.float32)
        for i in reversed(range(self.n_chunks)):
            w = self._fetch_chunk(i)
            gw, g = chunk_bwd(w, boundaries[i], rope, g)
            m = self._chunk(self.opt_state_blocks["exp_avg"], i)
            v = self._chunk(self.opt_state_blocks["exp_avg_sq"], i)
            newp, newm, newv = self._chunk_update(
                w, gw, jax.tree_util.tree_map(jnp.asarray, m),
                jax.tree_util.tree_map(jnp.asarray, v),
                jnp.asarray(self.lr, jnp.float32), step)
            self._store_chunk(i, newp, newm, newv)  # page-out

        # ---- embedding/head params update on device
        (g_embed,) = embed_vjp(g)
        g_outer = jax.tree_util.tree_map(jnp.add, g_outer_head, g_embed)
        self.outer, self.opt_state_outer = self.optimizer.update(
            g_outer, self.opt_state_outer, self.outer, lr=self.lr)
        return loss

    def _store_chunk(self, i, newp, newm, newv):
        lo = i * self.chunk_layers

        def put(dst_tree, src_tree):
            for dst, src in zip(jax.tree_util.tree_leaves(dst_tree),
                                jax.tree_util.tree_leaves(src_tree)):
                dst[lo:lo + self.chunk_layers] = np.asarray(src)

        put(self.blocks_host, newp)
        put(self.opt_state_blocks["exp_avg"], newm)
        put(self.opt_state_blocks["exp_avg_sq"], newv)

    # ------------------------------------------------------------------
    def eval_loss(self, batch):
        """Loss without the update (streams chunks forward only)."""
        cfg = self.cfg
        input_ids = jnp.asarray(batch["input_ids"], jnp.int32)
        labels = jnp.concatenate(
            [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1)
        x = jnp.take(self.outer["wte"]["weight"].astype(self.compute_dtype),
                     input_ids, axis=0)
        if cfg.position_embedding == "learned":
            s = input_ids.shape[1]
            x = x + self.outer["wpe"]["weight"].astype(
                self.compute_dtype)[:s][None]
        chunk_fwd, _, rope = self._get_fns(input_ids.shape[1])
        for i in range(self.n_chunks):
            x = chunk_fwd(self._fetch_chunk(i), x, rope)
        hn = _norm_apply(cfg, self.outer["ln_f"], x)
        return self.model.head_ce(self.outer, hn, labels)
