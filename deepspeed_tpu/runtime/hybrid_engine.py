"""Hybrid (RLHF) engine: inference-path generation inside a training loop.

Reference ``runtime/hybrid_engine.py:32`` ``DeepSpeedHybridEngine``: an RLHF
actor must interleave fast autoregressive generation (rollouts) with ZeRO-3
training steps on the SAME weights. The reference rebuilds inference containers
around the training params and flips between layouts per phase (``generate``
:168, ``_zero3_forward`` :333). TPU-native, both phases are just different
compiled programs over one sharded param tree:

- training: the engine's fused fwd+bwd / apply programs (inherited);
- generation: a jitted prefill + KV-cache decode scan (``models/decoding.py``)
  reading the SAME fp32 masters, cast to the serving dtype inside the program —
  the SPMD partitioner inserts whatever gathers the ZeRO/TP layout needs, so
  there is no layout flip, no weight copy, and nothing to invalidate when the
  optimizer steps (a new params tree simply feeds the same compiled decode).

LoRA (reference ``:120-146`` fuse/unfuse): adapters fuse into a temporary
param tree for generation (one jitted tree-add) and never touch the masters —
"unfuse" is dropping the temporary.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .engine import DeepSpeedEngine
from ..config.base import ConfigError


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + in-loop generation. Enabled by the
    ``hybrid_engine.enabled`` config section (reference
    ``deepspeed/__init__.py:143`` engine selection)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.pipe_stages > 1:
            raise ConfigError(
                "hybrid_engine: generation inside a pipeline-parallel mesh is "
                "not supported (generate on a dp/tp mesh, train anywhere)")
        self._gen_cache = {}
        self._lora = None
        self._lora_scale = 1.0
        self._fuse_fn = None

    # -- LoRA (reference hybrid_engine.py:120 _fuse_lora / :146 _unfuse_lora)
    def set_lora(self, adapters, scale=1.0):
        self._lora = adapters
        self._lora_scale = scale
        self._fuse_fn = None

    def _gen_params(self):
        if self._lora is None:
            return self.params
        if self._fuse_fn is None:
            from ..ops.lora import fuse_lora

            with self.mesh:
                self._fuse_fn = jax.jit(
                    lambda p, a: fuse_lora(p, a, self._lora_scale),
                    out_shardings=self.param_shardings)
        return self._fuse_fn(self.params, self._lora)

    # -- generation ---------------------------------------------------------
    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 greedy=True, rng=None):
        """Rollout generation on the live training weights.

        input_ids: [b, prompt_len] int32. Returns [b, prompt + new] int32.
        Compiled per (batch, prompt_len, max_new_tokens, greedy) — sampling
        temperature/top_k are runtime args, not compile keys.
        """
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, prompt_len = input_ids.shape
        model = self.module
        he_cfg = self._config.hybrid_engine
        if max_new_tokens > he_cfg.max_out_tokens:
            raise ConfigError(
                f"generate: max_new_tokens {max_new_tokens} exceeds "
                f"hybrid_engine.max_out_tokens {he_cfg.max_out_tokens}")
        if prompt_len + max_new_tokens > model.config.max_seq_len:
            raise ConfigError(
                f"generate: {prompt_len + max_new_tokens} exceeds model "
                f"max_seq_len {model.config.max_seq_len}")
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        if isinstance(temperature, (int, float)) and temperature == 0.0:
            greedy = True

        # prompt-length bucketing, same scheme as the serving engine: rollout
        # prompts vary per PPO batch, and each distinct length must not
        # recompile (pad right, thread the true length as a traced scalar)
        bucket = max(int(he_cfg.prompt_bucket_size), 1)
        padded_len = min(-(-prompt_len // bucket) * bucket,
                         model.config.max_seq_len - max_new_tokens)
        padded_len = max(padded_len, prompt_len)
        max_len = padded_len + max_new_tokens
        ids_in = jnp.pad(input_ids, ((0, 0), (0, padded_len - prompt_len))) \
            if padded_len > prompt_len else input_ids
        true_len = jnp.asarray(prompt_len, jnp.int32)

        key = (b, padded_len, max_new_tokens, bool(greedy), int(top_k))
        if key not in self._gen_cache:
            from ..models.decoding import decode_tokens, prefill_and_first_token

            dtype = self.compute_dtype

            def rollout(params, ids, rng, temperature, true_len):
                cast = jax.tree_util.tree_map(lambda a: a.astype(dtype), params)
                rng, r0 = jax.random.split(rng)
                tok, cache = prefill_and_first_token(
                    model, cast, ids, r0, temperature, max_len=max_len,
                    greedy=greedy, top_k=top_k, dtype=dtype, true_len=true_len)
                toks = None
                if max_new_tokens > 1:
                    toks, _ = decode_tokens(
                        model, cast, cache, tok, rng, temperature,
                        prompt_len=true_len, max_len=max_len,
                        steps=max_new_tokens - 1, greedy=greedy, top_k=top_k)
                return tok, toks

            with self.mesh:
                self._gen_cache[key] = jax.jit(rollout)
        gen = self._gen_cache[key]
        tok, toks = gen(self._gen_params(), ids_in, rng,
                        jnp.asarray(temperature, jnp.float32), true_len)
        pieces = [input_ids, tok[:, None]]
        if toks is not None:
            pieces.append(jnp.transpose(toks))
        return jnp.concatenate(pieces, axis=1)

    def sequence_logprobs(self, input_ids, prompt_len):
        """Per-token logprobs of the generated suffix under the CURRENT params
        — the policy-gradient side of the RLHF loop (the critic/reward live
        outside the engine, as in the reference's DeepSpeed-Chat usage).
        Compiled once per (batch, seq, prompt_len) shape."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        key = ("logprobs", input_ids.shape, prompt_len)
        if key not in self._gen_cache:
            def lp(params, ids):
                cast = jax.tree_util.tree_map(
                    lambda a: a.astype(self.compute_dtype), params)
                logits = self.module.apply(cast, ids)
                logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
                tgt = ids[:, 1:]
                tok_lp = jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
                return tok_lp[:, prompt_len - 1:]

            with self.mesh:
                self._gen_cache[key] = jax.jit(lp)
        return self._gen_cache[key](self.params, input_ids)
