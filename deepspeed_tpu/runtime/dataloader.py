"""Data loading.

TPU-native equivalent of the reference's ``runtime/dataloader.py``
(``DeepSpeedDataLoader`` over a torch ``DistributedSampler``): a host-side batched
iterator producing numpy/jnp dict batches. Under SPMD each process feeds its
addressable shard of the global batch; single-host runs feed the whole batch and the
engine shards it onto the mesh via ``jax.device_put``.
"""

import numpy as np

from ..utils.logging import logger


class RepeatingLoader:
    """Reference ``runtime/dataloader.py`` RepeatingLoader: wrap an iterator to
    restart from the beginning when exhausted."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset of dict samples (or (x, y) tuples).

    process_shard: with multi-host data parallelism each process reads only its
    dp-rank slice (the reference's DistributedSampler); rank/num_shards come from
    the engine.
    """

    def __init__(self, dataset, batch_size, shuffle=False, seed=1234, drop_last=True,
                 collate_fn=None, rank=0, num_shards=1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        self.rank = rank
        self.num_shards = num_shards
        self.epoch = 0
        if len(dataset) < batch_size * num_shards:
            logger.warning(
                f"Dataset of {len(dataset)} samples smaller than global batch "
                f"{batch_size * num_shards}"
            )

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        n = len(self.dataset) // self.num_shards
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        indices = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(indices)
        # contiguous shard per dp rank
        shard = indices[self.rank::self.num_shards]
        n_batches = len(self)
        for b in range(n_batches):
            idx = shard[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn([self.dataset[int(i)] for i in idx])


def default_collate(samples):
    """Stack dict-of-array samples (or (x, y) tuples) into a dict batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)) and len(first) == 2:
        xs = np.stack([np.asarray(s[0]) for s in samples])
        ys = np.stack([np.asarray(s[1]) for s in samples])
        return {"x": xs, "y": ys}
    return {"x": np.stack([np.asarray(s) for s in samples])}
