"""Curriculum learning scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py`` — full file).

Schedules a difficulty value (canonically: sequence length) over training steps.
Same schedule types as the reference: ``fixed_linear``, ``fixed_root``,
``fixed_discrete``, ``custom``. The engine truncates each batch to the current
difficulty (the reference's seqlen curriculum hook, ``engine.py:1675``).
"""

import math

from ...config.base import ConfigError


class CurriculumScheduler:
    def __init__(self, config):
        config = dict(config or {})
        self.state = {}
        for key in ("curriculum_type", "min_difficulty", "max_difficulty",
                    "schedule_type"):
            if key not in config:
                raise ConfigError(f"Curriculum learning requires the config '{key}'")
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        self.state["schedule_type"] = config["schedule_type"]
        schedule_config = dict(config.get("schedule_config", {}))

        if self.state["schedule_type"] == "fixed_discrete":
            # {"difficulty": [1,2,3], "max_step": [5,10]}
            if "difficulty" not in schedule_config:
                raise ConfigError("fixed_discrete schedule requires 'difficulty'")
            if "max_step" not in schedule_config:
                raise ConfigError("fixed_discrete schedule requires 'max_step'")
            if len(schedule_config["max_step"]) > 0:
                if len(schedule_config["difficulty"]) != len(schedule_config["max_step"]) + 1:
                    raise ConfigError("len(difficulty) must be len(max_step) + 1")
        elif self.state["schedule_type"] in ("fixed_linear", "fixed_root"):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in schedule_config:
                    raise ConfigError(f"{self.state['schedule_type']} requires '{key}'")
            if schedule_config["difficulty_step"] % 8:
                # the reference warns: seqlen not multiple of 8 hurts tensor cores;
                # on TPU the MXU lane width makes multiples of 128 ideal, 8 minimum
                from ...utils.logging import logger

                logger.warning(
                    "difficulty_step not a multiple of 8 can underutilize the MXU")
            if self.state["schedule_type"] == "fixed_root" \
                    and "root_degree" not in schedule_config:
                raise ConfigError("fixed_root requires 'root_degree'")
        elif self.state["schedule_type"] != "custom":
            raise ConfigError(
                f"Unsupported curriculum schedule type {self.state['schedule_type']}")
        self.state["schedule"] = schedule_config
        self.custom_get_difficulty = None

    # ----------------------------------------------------------------------------
    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn

    def get_state(self):
        return dict(self.state)

    def set_state(self, state):
        self.state.update(state)

    def _fixed_linear(self, global_steps):
        s = self.state["schedule"]
        frac = min(1.0, global_steps / s["total_curriculum_step"])
        diff = self.state["min_difficulty"] + frac * (
            self.state["max_difficulty"] - self.state["min_difficulty"])
        step = s["difficulty_step"]
        return min(self.state["max_difficulty"],
                   int(diff // step) * step if diff >= step else int(diff))

    def _fixed_root(self, global_steps):
        s = self.state["schedule"]
        frac = min(1.0, global_steps / s["total_curriculum_step"])
        frac = frac ** (1.0 / s["root_degree"])
        diff = self.state["min_difficulty"] + frac * (
            self.state["max_difficulty"] - self.state["min_difficulty"])
        step = s["difficulty_step"]
        return min(self.state["max_difficulty"],
                   int(diff // step) * step if diff >= step else int(diff))

    def _fixed_discrete(self, global_steps):
        s = self.state["schedule"]
        for i, max_step in enumerate(s["max_step"]):
            if global_steps <= max_step:
                return s["difficulty"][i]
        return s["difficulty"][-1]

    def update_difficulty(self, global_steps):
        t = self.state["schedule_type"]
        if t == "fixed_linear":
            d = self._fixed_linear(global_steps)
        elif t == "fixed_root":
            d = self._fixed_root(global_steps)
        elif t == "fixed_discrete":
            d = self._fixed_discrete(global_steps)
        else:
            if self.custom_get_difficulty is None:
                raise ConfigError("custom schedule requires set_custom_get_difficulty")
            d = self.custom_get_difficulty(global_steps)
        self.state["current_difficulty"] = max(self.state["min_difficulty"],
                                               min(self.state["max_difficulty"], d))
        return self.state["current_difficulty"]
