"""Memory-mapped indexed dataset + offline data analyzer.

Reference: ``runtime/data_pipeline/data_sampling/indexed_dataset.py:369``
``MMapIndexedDataset`` (Megatron-derived binary format: a .bin of concatenated
token arrays + a .idx with dtype/sizes/pointers) and ``data_analyzer.py:20``
(map-reduce over a dataset computing per-sample metrics -> index files the
curriculum sampler consumes).

Same on-disk capability, reimplemented simply: the index is a small npz (sizes
+ pointers + dtype code), the payload one flat .bin consumed through
``np.memmap`` — random access to sample i costs one slice of the mapping, no
deserialization, and the file is shareable across processes.
"""

import json
import os

import numpy as np

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class MMapIndexedDatasetBuilder:
    def __init__(self, path, dtype=np.uint16):
        self.path = path
        self.dtype = np.dtype(dtype)
        self._bin = open(path + ".bin", "wb")
        self.sizes = []

    def add_item(self, tokens):
        arr = np.asarray(tokens, self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self.sizes.append(arr.size)

    def finalize(self):
        self._bin.close()
        sizes = np.asarray(self.sizes, np.int64)
        pointers = np.concatenate([[0], np.cumsum(sizes[:-1])]) \
            if sizes.size else np.zeros(0, np.int64)
        np.savez(self.path + ".idx.npz", sizes=sizes, pointers=pointers,
                 dtype_code=np.asarray(_CODES[self.dtype]))
        return self.path


class MMapIndexedDataset:
    """Random access over the built files; samples are 1-D token arrays."""

    def __init__(self, path):
        idx = np.load(path + ".idx.npz")
        self.sizes = idx["sizes"]
        self.pointers = idx["pointers"]
        self.dtype = np.dtype(_DTYPES[int(idx["dtype_code"])])
        self._mmap = np.memmap(path + ".bin", dtype=self.dtype, mode="r")

    def __len__(self):
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        p, n = int(self.pointers[i]), int(self.sizes[i])
        return np.asarray(self._mmap[p:p + n])


class DataAnalyzer:
    """Offline per-sample metric pass (reference ``data_analyzer.py:20``
    ``DataAnalyzer.run_map/run_reduce``): computes metric values for every
    sample, writes a metric->sample index usable as a curriculum difficulty
    table. ``metric_fns``: {name: fn(sample)->scalar}."""

    def __init__(self, dataset, metric_fns, save_path, num_workers=1,
                 worker_id=0):
        self.dataset = dataset
        self.metric_fns = metric_fns
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        os.makedirs(save_path, exist_ok=True)

    def run_map(self):
        """This worker's shard of the metric pass (map phase)."""
        n = len(self.dataset)
        lo = n * self.worker_id // self.num_workers
        hi = n * (self.worker_id + 1) // self.num_workers
        out = {name: np.empty(hi - lo, np.float64)
               for name in self.metric_fns}
        for j, i in enumerate(range(lo, hi)):
            sample = self.dataset[i]
            for name, fn in self.metric_fns.items():
                out[name][j] = float(fn(sample))
        np.savez(os.path.join(self.save_path,
                              f"metrics-{self.worker_id}.npz"),
                 lo=lo, hi=hi, **out)

    def run_reduce(self):
        """Merge worker shards; emit, per metric: the full value array plus a
        difficulty-sorted sample index (what the curriculum sampler consumes)."""
        shards = sorted(f for f in os.listdir(self.save_path)
                        if f.startswith("metrics-"))
        per_metric = {name: {} for name in self.metric_fns}
        total = 0
        for f in shards:
            blob = np.load(os.path.join(self.save_path, f))
            lo = int(blob["lo"])
            total = max(total, int(blob["hi"]))
            for name in self.metric_fns:
                per_metric[name][lo] = blob[name]
        result = {}
        for name, chunks in per_metric.items():
            values = np.concatenate([chunks[k] for k in sorted(chunks)])
            order = np.argsort(values, kind="stable")
            np.savez(os.path.join(self.save_path, f"index-{name}.npz"),
                     values=values, sample_order=order)
            result[name] = {"values": values, "sample_order": order}
        with open(os.path.join(self.save_path, "summary.json"), "w") as f:
            json.dump({name: {"min": float(np.min(r["values"])),
                              "max": float(np.max(r["values"])),
                              "count": int(r["values"].size)}
                       for name, r in result.items()}, f, indent=1)
        return result
