"""Deterministic resumable distributed sampler (reference
``runtime/data_pipeline/data_sampling/data_sampler.py:36`` DeepSpeedDataSampler).

Yields per-rank index batches for a dataset, deterministically from (seed, epoch,
consumed_samples) so training can resume mid-epoch after preemption — the core of
the reference's data-efficiency sampling (random-LTD / curriculum build on it).
"""

import numpy as np


class DeepSpeedDataSampler:
    def __init__(self, total_samples, micro_batch_size, data_parallel_rank,
                 data_parallel_size, *, drop_last=True, shuffle=True, seed=1234,
                 consumed_samples=0, gradient_accumulation_steps=1):
        self.total_samples = int(total_samples)
        self.micro_batch_size = int(micro_batch_size)
        self.dp_rank = int(data_parallel_rank)
        self.dp_size = int(data_parallel_size)
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.consumed_samples = int(consumed_samples)
        self.gas = int(gradient_accumulation_steps)
        if self.dp_rank >= self.dp_size:
            raise ValueError(
                f"rank {self.dp_rank} out of range for dp size {self.dp_size}")
        self.micro_batch_times_dp = self.micro_batch_size * self.dp_size
        if self.drop_last and self.total_samples < self.micro_batch_times_dp:
            raise ValueError(
                f"total_samples={self.total_samples} < micro_batch*dp="
                f"{self.micro_batch_times_dp} with drop_last: no batch can ever "
                "be formed")

    def __len__(self):
        n = self.total_samples - (self.consumed_samples % self.total_samples)
        if self.drop_last:
            return n // self.micro_batch_times_dp
        return (n + self.micro_batch_times_dp - 1) // self.micro_batch_times_dp

    def _epoch_order(self, epoch):
        if not self.shuffle:
            return np.arange(self.total_samples)
        rng = np.random.RandomState(self.seed + epoch)
        return rng.permutation(self.total_samples)

    def __iter__(self):
        """Yield [micro_batch_size] index lists for THIS dp rank, resuming at
        consumed_samples."""
        while True:
            epoch = self.consumed_samples // self.total_samples
            offset = self.consumed_samples % self.total_samples
            order = self._epoch_order(epoch)
            avail = self.total_samples - offset
            n_batches = avail // self.micro_batch_times_dp
            if n_batches == 0:
                if self.drop_last:
                    # skip the ragged tail into the next epoch
                    self.consumed_samples += avail
                    continue
                n_batches = 1
            for b in range(n_batches):
                start = offset + b * self.micro_batch_times_dp
                window = order[start:start + self.micro_batch_times_dp]
                shard = window[self.dp_rank * self.micro_batch_size:
                               (self.dp_rank + 1) * self.micro_batch_size]
                self.consumed_samples += self.micro_batch_times_dp
                yield shard.tolist()
            return

    # resume support (reference sampler state_dict pattern)
    def state_dict(self):
        return {"consumed_samples": self.consumed_samples, "seed": self.seed}

    def load_state_dict(self, state):
        self.consumed_samples = int(state["consumed_samples"])
        self.seed = state.get("seed", self.seed)
