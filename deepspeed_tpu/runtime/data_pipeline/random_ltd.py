"""Random layerwise token dropping (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/basic_layer.py:14``
``RandomLayerTokenDrop`` + its scheduler (``:38``) and the CUDA
gather/scatter kernels (``csrc/random_ltd/``): middle transformer layers
process a random SUBSET of tokens; the skipped tokens bypass the layer via
the residual stream. The kept-token count follows a schedule that anneals to
the full sequence.

TPU-native: the gather/scatter kernels are ``jnp.take_along_axis`` /
``scatter`` (XLA fuses them); the random subset is drawn per layer per step
with a sorted index so relative order (and causal masking) is preserved.
"""

import jax
import jax.numpy as jnp


def random_token_select(rng, seq_len, keep):
    """Sorted random subset of ``keep`` positions out of ``seq_len``."""
    scores = jax.random.uniform(rng, (seq_len,))
    idx = jnp.argsort(scores)[:keep]
    return jnp.sort(idx)


def ltd_gather(x, idx):
    """x: [b, s, d]; idx: [keep] -> [b, keep, d]."""
    return jnp.take(x, idx, axis=1)


def ltd_scatter(x_full, x_kept, idx):
    """Write the processed kept tokens back; dropped tokens keep the residual
    input (the layer is skipped for them)."""
    return x_full.at[:, idx].set(x_kept)


def apply_random_ltd(block_fn, x, rng, keep, *block_args, **block_kw):
    """Run ``block_fn`` on a random ``keep``-token subsequence of x.

    Returns the full-sequence output where non-kept tokens passed through
    unchanged. ``keep`` is static (shapes are compiled)."""
    s = x.shape[1]
    if keep >= s:
        return block_fn(x, *block_args, **block_kw)
    idx = random_token_select(rng, s, keep)
    sub = ltd_gather(x, idx)
    sub_out = block_fn(sub, *block_args, **block_kw)
    return ltd_scatter(x, sub_out, idx)


class RandomLTDScheduler:
    """Kept-token schedule (reference ``data_routing/scheduler.py``): linear
    anneal from ``start_seq`` to the full length over ``total_steps``, in
    ``step_size`` granules."""

    def __init__(self, full_seq, start_seq, total_steps, step_size=16):
        self.full_seq = full_seq
        self.start_seq = min(start_seq, full_seq)
        self.total_steps = max(1, total_steps)
        self.step_size = step_size
        self.global_step = 0

    def keep_at(self, step):
        frac = min(1.0, step / self.total_steps)
        if frac >= 1.0:
            return self.full_seq  # fully annealed regardless of granularity
        raw = self.start_seq + frac * (self.full_seq - self.start_seq)
        granular = int(raw // self.step_size * self.step_size)
        return int(min(self.full_seq, max(self.start_seq, granular)))

    def step(self):
        self.global_step += 1
        return self.keep_at(self.global_step)

    def state_dict(self):
        return {"global_step": self.global_step}

    def load_state_dict(self, state):
        self.global_step = state["global_step"]
