from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler

__all__ = ["CurriculumScheduler", "DeepSpeedDataSampler"]
