from .engine import DeepSpeedEngine
from .dataloader import DeepSpeedDataLoader, RepeatingLoader

__all__ = ["DeepSpeedEngine", "DeepSpeedDataLoader", "RepeatingLoader"]
