"""Optimizer-state offload: host RAM (ZeRO-Offload) and NVMe (ZeRO-Infinity).

TPU-native equivalent of the reference's offload stack:
- ``CPUAdamBuilder`` AVX kernels (``csrc/adam/cpu_adam.cpp``) -> the optimizer
  update jitted for the host CPU (XLA CPU vectorizes; placement is forced with
  ``jax.default_device``), fp32 masters + optimizer state live in host RAM while
  the device holds compute-dtype params only;
- ``runtime/swap_tensor/partitioned_optimizer_swapper.py:218`` +
  ``pipelined_optimizer_swapper.py`` -> ``NvmeStateStore``: one file per state
  leaf, read-ahead window + write-behind through the C++ aio thread pool
  (``ops/aio.py``), so disk traffic overlaps with the per-leaf update compute.

Data flow per step (reference ZeRO-Offload fig.): device grads -> host, host Adam
on masters, masters cast to compute dtype -> device. The engine drives this from
``DeepSpeedEngine.step`` when ``zero_optimization.offload_optimizer.device`` is
``cpu`` or ``nvme``.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.loss_scaler import global_grad_norm
from ..utils.logging import log_dist


def _cpu_device():
    return jax.local_devices(backend="cpu")[0]


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    keys = ["/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            for path, _ in flat]
    return keys, [l for _, l in flat], treedef


class NvmeStateStore:
    """Per-leaf disk store with async read-ahead and write-behind."""

    def __init__(self, nvme_path, aio_threads=4, window=4):
        from ..ops.aio import AsyncIOHandle

        self.dir = os.path.join(nvme_path, "ds_tpu_optimizer_swap")
        os.makedirs(self.dir, exist_ok=True)
        self.aio = AsyncIOHandle(n_threads=aio_threads)
        self.window = window
        self.meta = {}  # key -> (shape, dtype)

    def _path(self, key):
        return os.path.join(self.dir, key.replace("/", "__") + ".bin")

    def write_leaf(self, key, array, wait=False):
        arr = np.asarray(array)
        self.meta[key] = (arr.shape, arr.dtype)
        req = self.aio.write(self._path(key), arr)
        if wait:
            self.aio.wait(req)
        return req

    def start_read(self, key):
        shape, dtype = self.meta[key]
        buf = np.empty(shape, dtype)
        req = self.aio.read(self._path(key), buf)
        return req, buf

    def finish(self, req):
        self.aio.wait(req)

    def drain(self):
        self.aio.wait_all()


class OffloadedOptimizer:
    """Host-side optimizer with fp32 masters; state in RAM or on NVMe.

    API mirrors the in-engine path: ``step(grads, lr, scale_inv) ->
    (device_params, grad_norm)`` where ``device_params`` are compute-dtype copies
    placed per the engine's shardings.
    """

    def __init__(self, optimizer, master_params, wd_mask, *, compute_dtype,
                 param_shardings, device="cpu", nvme_path="", aio_threads=4,
                 clip=0.0):
        self.optimizer = optimizer
        self.wd_mask = wd_mask
        self.compute_dtype = compute_dtype
        self.param_shardings = param_shardings
        self.clip = clip
        self.device = device
        self.cpu = _cpu_device()

        # Native fused host step (reference CPUAdamBuilder role,
        # csrc/adam/cpu_adam.cpp): in-place SIMD/OpenMP update over numpy
        # leaves for adam/adamw/adagrad on the full-host path. Decided BEFORE
        # the jax masters/state are built — the native path keeps everything
        # in numpy, and building the XLA-CPU copies first would transiently
        # double host RAM on exactly the large-model configs offload targets.
        # Opt out with DS_TPU_NATIVE_CPU_OPT=0; any ineligibility falls back
        # to the jitted XLA-CPU step transparently.
        self._native = None
        if device == "cpu" and \
                os.environ.get("DS_TPU_NATIVE_CPU_OPT", "1") != "0":
            from ..ops import cpu_adam_native
            from ..ops.optimizers import Adam, Adagrad

            if type(optimizer) in (Adam, Adagrad) and cpu_adam_native.available():
                self._native = "adam" if isinstance(optimizer, Adam) else "adagrad"

        if self._native:
            from ..ops.optimizers import _mask_like

            keys, leaves, treedef = _leaf_paths(master_params)
            # explicit copy: device_get returns READ-ONLY buffers
            np_masters = [np.array(jax.device_get(x), np.float32, copy=True)
                          for x in leaves]
            # the masters tree aliases the SAME mutable numpy buffers the
            # kernels update in place; _device_params reads them fresh
            self.masters = jax.tree_util.tree_unflatten(treedef, np_masters)
            self._nat_masters = np_masters
            self._nat_treedef = treedef
            self._nat_decay = [bool(np.asarray(d)) for d in
                               _leaf_paths(_mask_like(wd_mask, self.masters))[1]]
            if self._native == "adam":
                self._nat_m = [np.zeros_like(x) for x in np_masters]
                self._nat_v = [np.zeros_like(x) for x in np_masters]
            else:
                self._nat_s = [np.zeros_like(x) for x in np_masters]
            self._nat_step = 0
            self.store = None
            self.state = None
            self._full_update = None
            self._leaf_update = {}
            log_dist(f"native cpu_{self._native}: fused host step over "
                     f"{len(np_masters)} leaves", ranks=[0])
            return

        # fp32 masters in host RAM (committed to the CPU backend)
        self.masters = jax.tree_util.tree_map(
            lambda p: jax.device_put(np.asarray(jax.device_get(p), np.float32),
                                     self.cpu),
            master_params)

        with jax.default_device(self.cpu):
            state = optimizer.init(self.masters)

        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_optimizer.device=nvme requires nvme_path")
            self.store = NvmeStateStore(nvme_path, aio_threads=aio_threads)
            self.step_count = np.asarray(jax.device_get(state["step"]))
            self._state_heads = [k for k in state if k != "step"]
            for head in self._state_heads:
                keys, leaves, treedef = _leaf_paths(state[head])
                for k, leaf in zip(keys, leaves):
                    self.store.write_leaf(f"{head}/{k}", jax.device_get(leaf))
            self.store.drain()
            self._treedef = treedef
            self._master_keys, self._master_leaves, self._master_treedef = \
                _leaf_paths(self.masters)
            self._wd_leaves = _leaf_paths(wd_mask)[1]
            self.state = None
            log_dist(f"NVMe optimizer offload: {len(self._master_keys)} leaves -> "
                     f"{self.store.dir}", ranks=[0])
        else:
            self.store = None
            self.state = state

        self._full_update = None
        self._leaf_update = {}

    # ------------------------------------------------------------------------------
    def _to_host(self, grads, scale_inv):
        """Device grads -> host fp32, unscaled; also the global norm (host).

        All leaf transfers are STARTED asynchronously before any is consumed,
        so D2H copies overlap each other (and any still-running device work)
        instead of serializing leaf by leaf — the same overlap the reference
        gets from its side-stream grad copies (stage_1_and_2.py:1031)."""
        for g in jax.tree_util.tree_leaves(grads):
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        host = jax.tree_util.tree_map(
            lambda g: jax.device_put(np.asarray(jax.device_get(g)), self.cpu), grads)
        with jax.default_device(self.cpu):
            host = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * scale_inv, host)
            norm = global_grad_norm(host)
        return host, norm

    def _clip_factor(self, norm):
        if self.clip <= 0:
            return np.float32(1.0)
        return np.float32(min(1.0, self.clip / (float(norm) + 1e-6)))

    def _device_params(self):
        return jax.tree_util.tree_map(
            lambda m, s: jax.device_put(
                np.asarray(jax.device_get(m)).astype(
                    jnp.dtype(self.compute_dtype)), s),
            self.masters, self.param_shardings)

    def step(self, grads, lr, scale_inv=1.0):
        """Returns (device params in compute dtype, global grad norm, overflow).

        On non-finite gradients (fp16 overflow) the update is skipped — the
        reference FP16_Optimizer.step contract."""
        grads_host, norm = self._to_host(grads, float(scale_inv))
        if not np.isfinite(float(norm)):
            return self._device_params(), norm, True
        factor = self._clip_factor(norm)
        if self._native:
            self._native_step(grads_host, float(lr), float(factor))
        elif self.store is None:
            if self._full_update is None:
                def update(masters, state, grads, lr, factor):
                    grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
                    return self.optimizer.update(grads, state, masters, lr=lr,
                                                 wd_mask=self.wd_mask)

                self._full_update = jax.jit(update, donate_argnums=(0, 1))
            with jax.default_device(self.cpu):
                self.masters, self.state = self._full_update(
                    self.masters, self.state, grads_host,
                    jnp.asarray(lr, jnp.float32), jnp.asarray(factor))
        else:
            self._nvme_step(grads_host, lr, factor)
        return self._device_params(), norm, False

    def _native_step(self, grads_host, lr, factor):
        """Fused in-place host update (csrc/adam/cpu_adam.cpp) — one kernel
        call per leaf, masters/moments mutated in their numpy buffers."""
        from ..ops import cpu_adam_native

        opt = self.optimizer
        grads = [np.ascontiguousarray(np.asarray(jax.device_get(g), np.float32))
                 for g in _leaf_paths(grads_host)[1]]
        self._nat_step += 1
        for i, (p, g) in enumerate(zip(self._nat_masters, grads)):
            if self._native == "adam":
                cpu_adam_native.adam_step_inplace(
                    p, g, self._nat_m[i], self._nat_v[i],
                    step=self._nat_step, lr=lr, betas=opt.betas, eps=opt.eps,
                    weight_decay=opt.weight_decay, adamw_mode=opt.adam_w_mode,
                    bias_correction=opt.bias_correction,
                    decay=self._nat_decay[i], grad_scale=factor)
            else:
                cpu_adam_native.adagrad_step_inplace(
                    p, g, self._nat_s[i], lr=lr, eps=opt.eps,
                    weight_decay=opt.weight_decay, decay=self._nat_decay[i],
                    grad_scale=factor)

    # ------------------------------------------------------------------------------
    def _nvme_leaf_update(self, shape_dtype_key, master, grad, heads, lr, factor,
                          decay):
        """Per-leaf pipelined update (jit cached by leaf shape)."""
        if shape_dtype_key not in self._leaf_update:
            opt = self.optimizer

            def update(master, grad, heads, step, lr, factor):
                params = {"x": master}
                grads = {"x": grad * factor}
                state = {"step": step}
                for h, v in heads.items():
                    state[h] = {"x": v}
                newp, news = opt.update(grads, state, params, lr=lr,
                                        wd_mask={"x": decay})
                return newp["x"], {h: news[h]["x"] for h in heads}

            self._leaf_update[shape_dtype_key] = jax.jit(update,
                                                         donate_argnums=(0, 2))
        return self._leaf_update[shape_dtype_key]

    def _nvme_step(self, grads_host, lr, factor):
        keys = self._master_keys
        grads_leaves = _leaf_paths(grads_host)[1]
        window = self.store.window
        step = jnp.asarray(self.step_count)

        # read-ahead window
        pending = {}
        for i in range(min(window, len(keys))):
            pending[i] = {h: self.store.start_read(f"{h}/{keys[i]}")
                          for h in self._state_heads}

        new_masters = []
        with jax.default_device(self.cpu):
            for i, key in enumerate(keys):
                reads = pending.pop(i)
                heads = {}
                for h, (req, buf) in reads.items():
                    self.store.finish(req)
                    heads[h] = jnp.asarray(buf)
                nxt = i + window
                if nxt < len(keys):
                    pending[nxt] = {h: self.store.start_read(f"{h}/{keys[nxt]}")
                                    for h in self._state_heads}
                master = self._master_leaves[i]
                grad = jnp.asarray(grads_leaves[i])
                decay = bool(self._wd_leaves[i])
                fn = self._nvme_leaf_update(
                    (tuple(master.shape), str(master.dtype), decay),
                    master, grad, heads, lr, factor, decay)
                new_m, new_heads = fn(master, grad, heads, step,
                                      jnp.asarray(lr, jnp.float32),
                                      jnp.asarray(factor))
                # write-behind: submit and keep going
                for h, v in new_heads.items():
                    self.store.write_leaf(f"{h}/{key}", jax.device_get(v))
                new_masters.append(new_m)
        self.step_count = self.step_count + 1
        self.store.drain()
        self._master_leaves = new_masters
        self.masters = jax.tree_util.tree_unflatten(self._master_treedef,
                                                    new_masters)

    # ------------------------------------------------------------------------------
    # checkpoint surface (engine save/load)
    # ------------------------------------------------------------------------------
    def state_for_checkpoint(self):
        if self._native:
            unflat = lambda leaves: jax.tree_util.tree_unflatten(
                self._nat_treedef, [np.asarray(l) for l in leaves])
            state = {"step": np.asarray(self._nat_step, np.int32)}
            if self._native == "adam":
                state["exp_avg"] = unflat(self._nat_m)
                state["exp_avg_sq"] = unflat(self._nat_v)
            else:
                state["sum_sq"] = unflat(self._nat_s)
            return state
        if self.store is None:
            return self.state
        state = {"step": jnp.asarray(self.step_count)}
        for head in self._state_heads:
            reads = [self.store.start_read(f"{head}/{k}") for k in self._master_keys]
            leaves = []
            for req, buf in reads:
                self.store.finish(req)
                leaves.append(jnp.asarray(buf))
            state[head] = jax.tree_util.tree_unflatten(self._treedef, leaves)
        return state

    def load_state(self, state):
        if self._native:
            self._nat_step = int(np.asarray(state["step"]))
            heads = (("exp_avg", self._nat_m), ("exp_avg_sq", self._nat_v)) \
                if self._native == "adam" else (("sum_sq", self._nat_s),)
            for name, bufs in heads:
                for buf, leaf in zip(bufs, _leaf_paths(state[name])[1]):
                    buf[...] = np.asarray(jax.device_get(leaf), np.float32)
            return
        if self.store is None:
            self.state = jax.tree_util.tree_map(
                lambda l: jax.device_put(np.asarray(l), self.cpu), state)
            return
        self.step_count = np.asarray(jax.device_get(state["step"]))
        for head in self._state_heads:
            keys, leaves, _ = _leaf_paths(state[head])
            for k, leaf in zip(keys, leaves):
                self.store.write_leaf(f"{head}/{k}", jax.device_get(leaf))
        self.store.drain()

    def load_masters(self, params):
        if self._native:
            # refill the live numpy buffers in place (the masters tree keeps
            # aliasing them)
            for buf, leaf in zip(self._nat_masters, _leaf_paths(params)[1]):
                buf[...] = np.asarray(jax.device_get(leaf), np.float32)
            return
        self.masters = jax.tree_util.tree_map(
            lambda p: jax.device_put(np.asarray(jax.device_get(p), np.float32),
                                     self.cpu), params)
        self._master_keys, self._master_leaves, self._master_treedef = \
            _leaf_paths(self.masters)
