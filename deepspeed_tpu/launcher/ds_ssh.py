"""``ds_tpu_ssh`` — run a command on every hostfile host (reference
``bin/ds_ssh``): the pod-wide shell helper for checking env, clearing caches,
or pulling logs.

    ds_tpu_ssh -H hostfile "python -c 'import jax; print(jax.devices())'"
    ds_tpu_ssh -H hostfile --include worker-[0-1] -- nvidia-smi-equivalent
"""

import argparse
import subprocess
import sys

from .runner import fetch_hostfile, parse_inclusion_exclusion


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-H", "--hostfile", required=True)
    p.add_argument("--include", type=str, default="",
                   help="host filter (reference --include syntax)")
    p.add_argument("--exclude", type=str, default="")
    p.add_argument("--ssh_port", type=int, default=22)
    p.add_argument("--sequential", action="store_true",
                   help="one host at a time instead of concurrently")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host")
    args = p.parse_args(argv)
    cmd = " ".join(c for c in args.command if c != "--").strip()
    if not cmd:
        p.error("no command given")

    pool = fetch_hostfile(args.hostfile)
    if not pool:
        print(f"error: no hosts in {args.hostfile}", file=sys.stderr)
        return 1
    hosts = list(parse_inclusion_exclusion(pool, args.include, args.exclude))
    procs = []
    rc = 0
    for host in hosts:
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
                   "-p", str(args.ssh_port), host, cmd]
        if args.sequential:
            r = subprocess.run(ssh_cmd)
            print(f"[{host}] exit {r.returncode}")
            rc = rc or r.returncode
        else:
            procs.append((host, subprocess.Popen(ssh_cmd)))
    for host, proc in procs:
        proc.wait()
        print(f"[{host}] exit {proc.returncode}")
        rc = rc or proc.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
