from .runner import main, fetch_hostfile, parse_inclusion_exclusion

__all__ = ["main", "fetch_hostfile", "parse_inclusion_exclusion"]
