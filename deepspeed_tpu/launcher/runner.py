"""The ``ds_tpu`` CLI launcher.

TPU-native equivalent of the reference's ``deepspeed`` CLI
(``bin/deepspeed`` -> ``launcher/runner.py:376 main`` -> per-node
``launcher/launch.py:216``). On GPU clusters the launcher forks one process per
device and wires NCCL rendezvous env; on TPU the unit is one process per *host*
(all local chips belong to it), so:

- single host: exec the script in-process-count-1 mode (JAX sees all local chips);
- multi-host pods: each host runs the same command (GKE/`gcloud compute tpus
  tpu-vm ssh --worker=all`); this launcher sets the rendezvous env
  (``DS_TPU_COORDINATOR``/``DS_TPU_NUM_PROCESSES``/``DS_TPU_PROCESS_ID``) that
  ``deepspeed_tpu.comm.init_distributed`` consumes, from flags or TPU metadata.

Hostfile / --include / --exclude filters are parsed with the reference's syntax so
existing job scripts port.
"""

import argparse
import os
import subprocess
import sys

from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher", usage="ds_tpu [options] script.py [script args]"
    )
    parser.add_argument("--hostfile", type=str, default="",
                        help="hostfile (reference syntax: '<host> slots=<n>')")
    parser.add_argument("--include", type=str, default="",
                        help="hosts to include, e.g. 'worker-0@worker-1'")
    parser.add_argument("--exclude", type=str, default="",
                        help="hosts to exclude")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=8476)
    parser.add_argument("--node_rank", type=int, default=-1,
                        help="this host's index in the pod (auto from TPU metadata if unset)")
    parser.add_argument("--deepspeed_config", type=str, default=None)
    parser.add_argument("--module", action="store_true",
                        help="run the target as 'python -m <module>'")
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path):
    """Reference ``launcher/runner.py:188``: '<hostname> slots=<n>' lines."""
    if not path or not os.path.isfile(path):
        return {}
    resource_pool = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                resource_pool[hostname] = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile contains a bad entry: {line!r}")
    return resource_pool


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """Reference ``launcher/runner.py:243`` filter syntax: 'host1@host2'."""
    active = dict(resource_pool)
    if inclusion:
        wanted = set(inclusion.split("@"))
        unknown = wanted - set(active)
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {sorted(unknown)}")
        active = {h: s for h, s in active.items() if h in wanted}
    if exclusion:
        banned = set(exclusion.split("@"))
        unknown = banned - set(active)
        if unknown:
            raise ValueError(f"--exclude hosts not in hostfile: {sorted(unknown)}")
        active = {h: s for h, s in active.items() if h not in banned}
    return active


def main(args=None):
    args = parse_args(args)

    env = os.environ.copy()
    resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool:
        resource_pool = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
        hosts = sorted(resource_pool)
        num_nodes = len(hosts) if args.num_nodes < 0 else args.num_nodes
        master = args.master_addr or hosts[0]
        node_rank = args.node_rank
        if node_rank < 0:
            import socket

            hostname = socket.gethostname()
            node_rank = hosts.index(hostname) if hostname in hosts else 0
        env["DS_TPU_NUM_PROCESSES"] = str(num_nodes)
        env["DS_TPU_COORDINATOR"] = master
        env["DS_TPU_PROCESS_ID"] = str(node_rank)
        env["MASTER_PORT"] = str(args.master_port)
        logger.info(
            f"ds_tpu: pod launch — {num_nodes} hosts, coordinator {master}:"
            f"{args.master_port}, this host rank {node_rank}"
        )
    else:
        logger.info("ds_tpu: single-host launch (all local TPU chips)")

    if args.deepspeed_config:
        env["DS_TPU_CONFIG"] = args.deepspeed_config

    if args.module:
        cmd = [sys.executable, "-m", args.user_script] + args.user_args
    else:
        cmd = [sys.executable, args.user_script] + args.user_args
    result = subprocess.call(cmd, env=env)
    return result


if __name__ == "__main__":
    sys.exit(main())
