"""The ``ds_tpu`` CLI launcher.

TPU-native equivalent of the reference's ``deepspeed`` CLI
(``bin/deepspeed`` -> ``launcher/runner.py:376 main`` -> per-node
``launcher/launch.py:216``). On GPU clusters the launcher forks one process per
device and wires NCCL rendezvous env; on TPU the unit is one process per *host*
(all local chips belong to it), so:

- single host: exec the script in-process-count-1 mode (JAX sees all local chips);
- multi-host pods: each host runs the same command (GKE/`gcloud compute tpus
  tpu-vm ssh --worker=all`); this launcher sets the rendezvous env
  (``DS_TPU_COORDINATOR``/``DS_TPU_NUM_PROCESSES``/``DS_TPU_PROCESS_ID``) that
  ``deepspeed_tpu.comm.init_distributed`` consumes, from flags or TPU metadata.

Hostfile / --include / --exclude filters are parsed with the reference's syntax so
existing job scripts port.
"""

import argparse
import os
import subprocess
import sys

from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher", usage="ds_tpu [options] script.py [script args]"
    )
    parser.add_argument("--hostfile", type=str, default="",
                        help="hostfile (reference syntax: '<host> slots=<n>')")
    parser.add_argument("--include", type=str, default="",
                        help="hosts to include, e.g. 'worker-0@worker-1'")
    parser.add_argument("--exclude", type=str, default="",
                        help="hosts to exclude")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=8476)
    parser.add_argument("--node_rank", type=int, default=-1,
                        help="this host's index in the pod (auto from TPU metadata if unset)")
    parser.add_argument("--num_local_procs", type=int, default=0,
                        help="spawn N local worker processes (multi-host "
                             "simulated on this machine; CPU pods / tests)")
    parser.add_argument("--local_devices_per_proc", type=int, default=0,
                        help="with --num_local_procs: virtual CPU devices per "
                             "worker (0 = leave platform env untouched)")
    parser.add_argument("--ssh", action="store_true",
                        help="with --hostfile: launch the command on every "
                             "host over ssh (reference PDSH runner role)")
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--launcher", type=str, default="",
                        choices=["", "ssh", "pdsh", "slurm", "openmpi",
                                 "mpich", "mvapich"],
                        help="multi-node transport (reference --launcher): "
                             "ssh | pdsh | slurm (srun) | openmpi | mpich "
                             "(mpirun); one process per HOST either way")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra args passed through to srun/mpirun")
    parser.add_argument("--slurm_comment", type=str, default="",
                        help="slurm --comment (reference --comment flag)")
    parser.add_argument("--deepspeed_config", type=str, default=None)
    parser.add_argument("--module", action="store_true",
                        help="run the target as 'python -m <module>'")
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path):
    """Reference ``launcher/runner.py:188``: '<hostname> slots=<n>' lines."""
    if not path or not os.path.isfile(path):
        return {}
    resource_pool = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                resource_pool[hostname] = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile contains a bad entry: {line!r}")
    return resource_pool


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """Reference ``launcher/runner.py:243`` filter syntax: 'host1@host2'."""
    active = dict(resource_pool)
    if inclusion:
        wanted = set(inclusion.split("@"))
        unknown = wanted - set(active)
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {sorted(unknown)}")
        active = {h: s for h, s in active.items() if h in wanted}
    if exclusion:
        banned = set(exclusion.split("@"))
        unknown = banned - set(active)
        if unknown:
            raise ValueError(f"--exclude hosts not in hostfile: {sorted(unknown)}")
        active = {h: s for h, s in active.items() if h not in banned}
    return active


class SshRunner:
    """Multi-node command builder+executor over plain ssh — the reference's
    ``multinode_runner.py`` PDSH role (``:51``) without the pdsh dependency:
    one ssh per host, rendezvous env inlined into the remote command."""

    def __init__(self, hosts, master, master_port, ssh_port=22):
        self.hosts = list(hosts)
        self.master = master
        self.master_port = master_port
        self.ssh_port = ssh_port

    def build_cmds(self, cmd, extra_env=None):
        import shlex

        cmds = []
        for rank, host in enumerate(self.hosts):
            env = {
                "DS_TPU_NUM_PROCESSES": str(len(self.hosts)),
                "DS_TPU_COORDINATOR": self.master,
                "DS_TPU_PROCESS_ID": str(rank),
                "MASTER_PORT": str(self.master_port),
            }
            env.update(extra_env or {})
            exports = " ".join(f"{k}={shlex.quote(str(v))}"
                               for k, v in sorted(env.items()))
            remote = (f"cd {shlex.quote(os.getcwd())} && {exports} "
                      f"{' '.join(shlex.quote(c) for c in cmd)}")
            cmds.append(["ssh", "-p", str(self.ssh_port),
                         "-o", "StrictHostKeyChecking=no", host, remote])
        return cmds

    def run(self, cmd, extra_env=None):
        procs = [subprocess.Popen(c) for c in self.build_cmds(cmd, extra_env)]
        return _wait_kill_on_failure(procs)


def launch_local_procs(cmd, num_procs, env, devices_per_proc=0,
                       master_port=None):
    """Spawn ``num_procs`` local workers with the rendezvous env — multi-host
    simulated on one machine (the reference test-harness pattern,
    ``tests/unit/common.py:183``), also the real path for CPU pods."""
    import socket

    if master_port is None:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        master_port = s.getsockname()[1]
        s.close()
    procs = []
    for rank in range(num_procs):
        wenv = dict(env)
        wenv.update({
            "DS_TPU_NUM_PROCESSES": str(num_procs),
            "DS_TPU_COORDINATOR": "127.0.0.1",
            "DS_TPU_PROCESS_ID": str(rank),
            "MASTER_PORT": str(master_port),
        })
        if devices_per_proc:
            wenv["JAX_PLATFORMS"] = "cpu"
            wenv["XLA_FLAGS"] = (wenv.get("XLA_FLAGS", "") +
                                 f" --xla_force_host_platform_device_count="
                                 f"{devices_per_proc}").strip()
        procs.append(subprocess.Popen(cmd, env=wenv))
    return _wait_kill_on_failure(procs)


def _wait_kill_on_failure(procs, poll_s=0.5):
    """Wait for all workers, but terminate the rest as soon as one fails —
    a dead rank leaves its peers blocked in a collective forever (XLA has no
    collective timeout; the reference's launch.py kills siblings the same
    way, ``launcher/launch.py:119``)."""
    import time

    try:
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                return max(rcs) if rcs else 0
            if any(rc not in (None, 0) for rc in rcs):
                bad = next(i for i, rc in enumerate(rcs) if rc not in (None, 0))
                logger.error(
                    f"worker {bad} exited rc={rcs[bad]}; terminating the rest")
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = time.time() + 10
                for p in procs:
                    while p.poll() is None and time.time() < deadline:
                        time.sleep(0.1)
                    if p.poll() is None:
                        p.kill()
                return rcs[bad]
            time.sleep(poll_s)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main(args=None):
    args = parse_args(args)

    env = os.environ.copy()
    resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool:
        resource_pool = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
        hosts = list(resource_pool)
        num_nodes = len(hosts) if args.num_nodes < 0 else args.num_nodes
        master = args.master_addr or hosts[0]
        node_rank = args.node_rank
        if node_rank < 0:
            # FQDN/short matching in either direction (the same rule as
            # comm._rank_from_hostlist) — an exact-string lookup silently
            # gave every host rank 0 when the hostfile spelled FQDNs but
            # gethostname() returned short names
            from ..comm.comm import _rank_from_hostlist

            try:
                node_rank = _rank_from_hostlist(",".join(hosts))
            except RuntimeError as e:
                if "matches multiple" in str(e):
                    raise  # duplicate ranks would hang jax.distributed init
                node_rank = 0  # launching from a non-worker host
        env["DS_TPU_NUM_PROCESSES"] = str(num_nodes)
        env["DS_TPU_COORDINATOR"] = master
        env["DS_TPU_PROCESS_ID"] = str(node_rank)
        env["MASTER_PORT"] = str(args.master_port)
        logger.info(
            f"ds_tpu: pod launch — {num_nodes} hosts, coordinator {master}:"
            f"{args.master_port}, this host rank {node_rank}"
        )
    else:
        logger.info("ds_tpu: single-host launch (all local TPU chips)")

    if args.deepspeed_config:
        env["DS_TPU_CONFIG"] = args.deepspeed_config

    if args.module:
        cmd = [sys.executable, "-m", args.user_script] + args.user_args
    else:
        cmd = [sys.executable, args.user_script] + args.user_args

    if args.num_local_procs > 0:
        logger.info(f"ds_tpu: spawning {args.num_local_procs} local workers")
        return launch_local_procs(cmd, args.num_local_procs, env,
                                  devices_per_proc=args.local_devices_per_proc,
                                  master_port=None)
    if args.ssh and not args.launcher:
        args.launcher = "ssh"
    if args.launcher == "ssh" and not resource_pool:
        raise ValueError("--launcher ssh needs a non-empty --hostfile "
                         "(a missing path silently resolves to no hosts)")
    if args.launcher == "ssh":
        hosts = list(resource_pool)
        runner = SshRunner(hosts, args.master_addr or hosts[0],
                           args.master_port, ssh_port=args.ssh_port)
        extra = {"DS_TPU_CONFIG": args.deepspeed_config} \
            if args.deepspeed_config else None
        logger.info(f"ds_tpu: ssh launch on {len(hosts)} hosts")
        return runner.run(cmd, extra)
    if args.launcher == "pdsh":
        import shlex

        from .multinode import PDSHRunner

        if not resource_pool:
            raise ValueError("--launcher pdsh needs --hostfile")
        hosts = list(resource_pool)  # hostfile order = rank order (reference multinode_runner semantics)
        exports = {}
        if args.deepspeed_config:
            exports["DS_TPU_CONFIG"] = args.deepspeed_config
        runner = PDSHRunner(
            hosts, coordinator=args.master_addr or hosts[0],
            master_port=args.master_port, exports=exports,
            launcher_args=shlex.split(args.launcher_args), module=args.module)
        if not runner.backend_exists():
            logger.warning("ds_tpu: pdsh not found on PATH; the built "
                           "command may fail to execute")
        logger.info(f"ds_tpu: pdsh launch on {len(hosts)} hosts")
        return runner.run(args.user_script, args.user_args)
    if args.launcher in ("slurm", "openmpi", "mpich", "mvapich"):
        import shlex

        from .multinode import MULTINODE_RUNNERS

        # one process per host: hostfile slots are chips, which all belong to
        # the host process — the host count is what srun/mpirun see
        if resource_pool:
            num_hosts = len(resource_pool)
        elif args.num_nodes > 0:
            num_hosts = args.num_nodes
        else:
            raise ValueError(
                f"--launcher {args.launcher} needs --hostfile or --num_nodes")
        if not args.master_addr and not resource_pool:
            raise ValueError(
                f"--launcher {args.launcher} needs --master_addr when no "
                f"hostfile is given (the coordinator must be one of the hosts)")
        master = args.master_addr or list(resource_pool)[0]
        if args.launcher == "slurm" and resource_pool and not args.master_addr:
            # srun assigns SLURM_PROCID in Slurm's canonical (sorted) node
            # order, NOT --nodelist order — the default coordinator must be
            # the host that receives task 0, or every rank dials a host where
            # no jax.distributed coordinator listens
            master = sorted(resource_pool)[0]
        exports = {"DS_TPU_COORDINATOR": master,
                   "MASTER_PORT": str(args.master_port)}
        if args.deepspeed_config:
            exports["DS_TPU_CONFIG"] = args.deepspeed_config
        kw = dict(exports=exports,
                  launcher_args=shlex.split(args.launcher_args),
                  module=args.module)
        if args.launcher == "slurm":
            if resource_pool:
                # pin srun to the (already include/exclude-filtered) hostfile
                # hosts — otherwise the allocation may place no task on the
                # exported coordinator and every rank hangs at rendezvous.
                # Sorted: matches Slurm's canonical task-distribution order
                # (nodelist order is not honored by srun)
                kw.update(include="@".join(sorted(resource_pool)))
            else:
                kw.update(include=args.include, exclude=args.exclude)
            kw.update(comment=args.slurm_comment)
        else:
            if resource_pool:
                # hand mpirun the EFFECTIVE host set (filters applied, one
                # process per host), not the raw user hostfile — the raw file
                # still contains excluded hosts and chip-count slots. Each
                # flavor gets its own machinefile dialect: OpenMPI reads
                # "host slots=n", Hydra (MPICH) reads "host[:n]".
                import tempfile

                line = ("{h} slots=1\n" if args.launcher == "openmpi"
                        else "{h}\n")  # mpich/mvapich: plain host lines
                eff = tempfile.NamedTemporaryFile(
                    "w", prefix="ds_tpu_hosts_", suffix=".txt", delete=False)
                for h in resource_pool:
                    eff.write(line.format(h=h))
                eff.close()
                kw.update(hostfile=eff.name)
            else:
                kw.update(hostfile="")
        runner = MULTINODE_RUNNERS[args.launcher](num_hosts, **kw)
        if not runner.backend_exists():
            logger.warning(
                f"ds_tpu: {args.launcher} tooling not found on PATH; the "
                f"built command may fail to execute")
        logger.info(f"ds_tpu: {args.launcher} launch on {num_hosts} hosts")
        return runner.run(args.user_script, args.user_args)
    result = subprocess.call(cmd, env=env)
    return result


if __name__ == "__main__":
    sys.exit(main())
