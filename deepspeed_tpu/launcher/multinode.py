"""Slurm / OpenMPI / MPICH launch transports for TPU pods.

Behavior-port of the reference's multinode runners
(``launcher/multinode_runner.py:107`` OpenMPIRunner, ``:208`` SlurmRunner)
onto the TPU host model: the launch unit is one process per HOST (all local
chips belong to it), so both transports pin one task per node —
``--ntasks-per-node=1`` / ``--map-by ppr:1:node`` — where the reference
launches one process per GPU.

Rank numbering is the scheduler's job: these transports export only the
rendezvous *address* (``DS_TPU_COORDINATOR`` + ``MASTER_PORT``, and any
user ``--export``s); ``comm.init_distributed`` then reads the per-task rank
and world size from ``SLURM_PROCID``/``SLURM_NTASKS``,
``OMPI_COMM_WORLD_RANK``/``OMPI_COMM_WORLD_SIZE``, or MPICH's
``PMI_RANK``/``PMI_SIZE`` at startup. This replaces
the reference's base64 world-info blob threaded through ``launch.py``.
"""

import os
import shlex
import shutil
import subprocess
import sys

__all__ = ["PDSHRunner", "SlurmRunner", "OpenMPIRunner", "MPICHRunner",
           "MVAPICHRunner", "MULTINODE_RUNNERS"]


class _Transport:
    """Shared command-builder scaffolding for scheduler-based transports."""

    name = None

    def __init__(self, num_hosts, *, exports=None, launcher_args=None,
                 module=False):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        self.num_hosts = int(num_hosts)
        self.exports = dict(exports or {})
        self.launcher_args = list(launcher_args or [])
        self.module = module

    def backend_exists(self):
        raise NotImplementedError

    def build_cmd(self, user_script, user_args=()):
        raise NotImplementedError

    def _python_exec(self, user_script, user_args):
        py = [sys.executable, "-u"]
        if self.module:
            py.append("-m")
        return py + [user_script] + list(user_args)

    def run(self, user_script, user_args=()):
        return subprocess.call(self.build_cmd(user_script, user_args))


class SlurmRunner(_Transport):
    """``srun`` transport (reference ``multinode_runner.py:208``).

    One task per node; env forwarded via ``--export=ALL,K=V,...`` exactly as
    the reference does. ``--nodelist``/``--exclude``/``--nodes`` map the
    reference's include/exclude/num_nodes knobs onto srun's own flags.
    """

    name = "slurm"

    def __init__(self, num_hosts, *, include="", exclude="", comment="",
                 **kw):
        super().__init__(num_hosts, **kw)
        self.include = include
        self.exclude = exclude
        self.comment = comment

    def backend_exists(self):
        return bool(shutil.which("sinfo"))

    def build_cmd(self, user_script, user_args=()):
        cmd = ["srun", "-n", str(self.num_hosts), "--ntasks-per-node=1"]
        cmd += self.launcher_args
        if self.comment:
            cmd += ["--comment", self.comment]
        # hostfile filter syntax is '@'-separated; slurm nodelists are commas
        if self.include:
            cmd += ["--nodelist", self.include.replace("@", ",")]
        if self.exclude:
            cmd += ["--exclude", self.exclude.replace("@", ",")]
        exports = "--export=ALL"
        for k, v in sorted(self.exports.items()):
            if "," in str(v):
                # srun splits --export on commas; a comma in the value would
                # silently corrupt the forwarded environment
                raise ValueError(
                    f"slurm transport cannot forward {k}={v!r}: commas are "
                    f"--export separators")
            exports += f",{k}={v}"
        return cmd + [exports] + self._python_exec(user_script, user_args)


class OpenMPIRunner(_Transport):
    """``mpirun`` transport (reference ``multinode_runner.py:107``).

    One process per node via ``--map-by ppr:1:node``; env forwarded with
    ``-x K=V`` pairs as the reference does. The reference's GPU-centric
    ``--mca btl`` tuning is dropped — rank startup is plain TCP here and the
    data plane is ICI/DCN, owned by XLA rather than MPI.
    """

    name = "openmpi"

    def __init__(self, num_hosts, *, hostfile="", **kw):
        super().__init__(num_hosts, **kw)
        self.hostfile = hostfile

    def backend_exists(self):
        return bool(shutil.which("ompi_info"))

    def build_cmd(self, user_script, user_args=()):
        cmd = ["mpirun", "-n", str(self.num_hosts), "--map-by", "ppr:1:node"]
        if self.hostfile:
            cmd += ["-hostfile", self.hostfile]
        cmd += self.launcher_args
        for k, v in sorted(self.exports.items()):
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._python_exec(user_script, user_args)


class MPICHRunner(_Transport):
    """``mpirun`` (MPICH/Hydra) transport (reference ``multinode_runner.py:160``).

    One process per node via ``-ppn 1``; env forwarded with ``-genv K V``
    pairs (MPICH's spelling of OpenMPI's ``-x``). Rank numbering comes from
    the PMI env (``PMI_RANK``/``PMI_SIZE``) at startup."""

    name = "mpich"

    def __init__(self, num_hosts, *, hostfile="", **kw):
        super().__init__(num_hosts, **kw)
        self.hostfile = hostfile

    def backend_exists(self):
        # OpenMPI also installs an `mpirun`; make sure this one is Hydra/MPICH
        # (OpenMPI would reject -ppn/-genv/-f with no hint otherwise)
        if not shutil.which("mpirun"):
            return False
        try:
            out = subprocess.run(["mpirun", "--version"], capture_output=True,
                                 text=True, timeout=10)
            banner = (out.stdout + out.stderr).lower()
            return "hydra" in banner or "mpich" in banner
        except (OSError, subprocess.TimeoutExpired):
            return False

    def build_cmd(self, user_script, user_args=()):
        cmd = ["mpirun", "-n", str(self.num_hosts), "-ppn", "1"]
        if self.hostfile:
            cmd += ["-f", self.hostfile]
        cmd += self.launcher_args
        for k, v in sorted(self.exports.items()):
            cmd += ["-genv", k, str(v)]
        return cmd + self._python_exec(user_script, user_args)


class PDSHRunner(_Transport):
    """``pdsh`` transport (reference ``multinode_runner.py:51``).

    pdsh broadcasts ONE command line to every host (``-w h1,h2``), so unlike
    the ssh runner it cannot inline a per-host rank. Instead the command
    exports the host list itself (``DS_TPU_HOSTS``) and each process derives
    its rank from its own hostname's position at ``init_distributed`` time —
    the role the reference fills by threading a world-info blob through
    ``launch.py``. ``-S`` propagates the worst remote exit code; ``-f``
    matches the reference's fanout of 1024.
    """

    name = "pdsh"

    def __init__(self, hosts, *, coordinator=None, master_port=8476, **kw):
        """``hosts``: ordered host list (rank = position). ``coordinator``
        defaults to hosts[0]."""
        if isinstance(hosts, str):
            hosts = [h.strip() for h in hosts.split(",") if h.strip()]
        if not hosts:
            raise ValueError("pdsh transport needs a non-empty host list")
        super().__init__(len(hosts), **kw)
        self.hosts = list(hosts)
        if coordinator:
            # jax.distributed runs the coordinator service in PROCESS 0, and
            # rank = position in this list — so the coordinator host must be
            # first or every rank dials a host where nothing listens
            if coordinator not in self.hosts:
                raise ValueError(
                    f"pdsh coordinator {coordinator!r} is not in the host "
                    f"list {self.hosts}")
            self.hosts.remove(coordinator)
            self.hosts.insert(0, coordinator)
        self.coordinator = coordinator or self.hosts[0]
        self.master_port = int(master_port)

    def backend_exists(self):
        return bool(shutil.which("pdsh"))

    def build_cmd(self, user_script, user_args=()):
        env = {
            "DS_TPU_HOSTS": ",".join(self.hosts),
            "DS_TPU_NUM_PROCESSES": str(self.num_hosts),
            "DS_TPU_COORDINATOR": self.coordinator,
            "MASTER_PORT": str(self.master_port),
        }
        env.update(self.exports)
        exports = " ".join(f"export {k}={shlex.quote(str(v))};"
                           for k, v in sorted(env.items()))
        py = " ".join(shlex.quote(c)
                      for c in self._python_exec(user_script, user_args))
        remote = f"{exports} cd {shlex.quote(os.getcwd())} && {py}"
        # -R ssh on pdsh's OWN argv: the rcmd module is chosen before any
        # remote shell runs, so an exported env var could never select it
        return (["pdsh", "-S", "-R", "ssh", "-f", "1024",
                 "-w", ",".join(self.hosts)]
                + self.launcher_args + [remote])


class MVAPICHRunner(_Transport):
    """``mpirun`` (MVAPICH2/Hydra) transport (reference
    ``multinode_runner.py:256``).

    One process per node via ``-ppn 1``; env forwarded with ``-env K V``.
    Keeps the reference's DL-friendly MV2 defaults that apply off-GPU
    (``MV2_SUPPORT_DL``, affinity off for MPI_THREAD_MULTIPLE, CMA off,
    backtraces on); the CUDA-specific ones are dropped — the data plane here
    is ICI/DCN owned by XLA, MPI only bootstraps rank startup."""

    name = "mvapich"

    MV2_DEFAULTS = {
        "MV2_SMP_USE_CMA": "0",
        "MV2_DEBUG_SHOW_BACKTRACE": "1",
        "MV2_SUPPORT_DL": "1",
        "MV2_ENABLE_AFFINITY": "0",
    }

    def __init__(self, num_hosts, *, hostfile="", **kw):
        super().__init__(num_hosts, **kw)
        self.hostfile = hostfile
        for k, v in self.MV2_DEFAULTS.items():
            self.exports.setdefault(k, v)

    def backend_exists(self):
        # `mpiname` is MVAPICH's own id tool (reference checks its banner)
        if not shutil.which("mpiname"):
            return False
        try:
            out = subprocess.run(["mpiname"], capture_output=True, text=True,
                                 timeout=10)
            return "mvapich" in (out.stdout + out.stderr).lower()
        except (OSError, subprocess.TimeoutExpired):
            return False

    def build_cmd(self, user_script, user_args=()):
        cmd = ["mpirun", "-np", str(self.num_hosts), "-ppn", "1"]
        if self.hostfile:
            cmd += ["--hostfile", self.hostfile]
        cmd += self.launcher_args
        for k, v in sorted(self.exports.items()):
            cmd += ["-env", k, str(v)]
        return cmd + self._python_exec(user_script, user_args)


MULTINODE_RUNNERS = {r.name: r
                     for r in (PDSHRunner, SlurmRunner, OpenMPIRunner,
                               MPICHRunner, MVAPICHRunner)}
