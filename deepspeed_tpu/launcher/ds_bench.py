"""``ds_tpu_bench`` — collective micro-benchmark CLI.

Reference: ``bin/ds_bench`` -> DeepSpeedExamples communication benchmarks
(all_reduce/all_gather/all_to_all latency + algorithmic bandwidth sweeps).
Here the collectives are the framework's own comm facade compiled over the
local device mesh (real TPU chips or the virtual CPU mesh), which is what a
user tunes against before scaling out.

Usage: python -m deepspeed_tpu.launcher.ds_bench [--op all_reduce]
       [--min_mb 1] [--max_mb 64] [--trials 5]
"""

import argparse
import json
import time

import numpy as np


def run_sweep(op="all_reduce", min_mb=1, max_mb=64, trials=5, dtype="float32"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("data",))
    dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dtype]
    itemsize = np.dtype(np.float32).itemsize if dtype == "float32" else 2

    # size_mb is the PER-DEVICE payload; vol() = bytes each device moves on a
    # ring (NCCL-tests-style busbw accounting, so figures compare 1:1)
    def make_fn(op):
        if op == "all_reduce":
            f = lambda x: jax.lax.psum(x, "data")
            vol = lambda b: 2 * b * (n - 1) / n
        elif op == "all_gather":
            f = lambda x: jax.lax.all_gather(x, "data")
            vol = lambda b: b * (n - 1)  # receives everyone else's payload
        elif op == "reduce_scatter":
            f = lambda x: jax.lax.psum_scatter(x, "data", tiled=True)
            vol = lambda b: b * (n - 1) / n
        elif op == "all_to_all":
            f = lambda x: jax.lax.all_to_all(
                x.reshape(n, -1), "data", 0, 0, tiled=False).reshape(-1)
            vol = lambda b: b * (n - 1) / n
        else:
            raise ValueError(op)
        return f, vol

    f, vol = make_fn(op)
    sm = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("data"),
                               out_specs=P() if op == "all_reduce" else P("data"),
                               check_vma=False))

    results = []
    mb = min_mb
    while mb <= max_mb:
        per_dev = max(mb * 1024 * 1024 // itemsize, 1)
        per_dev = per_dev - per_dev % n if per_dev >= n else n
        elems = per_dev * n  # global length: each device holds size_mb
        x = jax.device_put(
            jnp.ones((elems,), dt),
            NamedSharding(mesh, P("data")))
        out = sm(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(trials):
            out = sm(x)
        jax.block_until_ready(out)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt_s = (time.perf_counter() - t0) / trials
        res = {
            "op": op, "size_mb": mb, "devices": n,
            "latency_us": round(dt_s * 1e6, 1),
            "algbw_gbps": round(vol(mb * 1024 * 1024) / dt_s / 1e9, 3),
        }
        results.append(res)
        print(json.dumps(res))
        mb *= 2
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="deepspeed_tpu collective benchmark")
    p.add_argument("--op", default="all_reduce",
                   choices=["all_reduce", "all_gather", "reduce_scatter",
                            "all_to_all"])
    p.add_argument("--min_mb", type=int, default=1)
    p.add_argument("--max_mb", type=int, default=64)
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    a = p.parse_args(argv)
    run_sweep(a.op, a.min_mb, a.max_mb, a.trials, a.dtype)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
