"""Inference engine (reference ``inference/engine.py:89`` InferenceEngine).

The reference wraps a HF torch model, surgically replaces blocks with fused CUDA
containers (``module_inject/replace_module.py:276``), slices weights per TP rank
(``ReplaceWithTensorSlicing:28``) and captures CUDA graphs (``:500``). TPU-native:

- TP = weight PartitionSpecs over the ``model`` mesh axis (the same logical-axis
  rules as training — auto-TP is the default, not a fallback);
- kernel injection = XLA fusion + the jitted decode step (a compiled program IS
  the captured graph — replay is free);
- KV-cache attention = ``models/decoding.py`` (the "softmax_context" kernel);
- checkpoint loading reuses the sharded npz checkpoint engine; TP resharding
  happens by construction (specs place each shard, the ``SDLoaderFactory``
  merge/split logic disappears).
"""

from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config.base import ConfigError
from ..config.config import MeshConfig
from ..models.layers import split_params_axes, Param
from ..models.decoding import init_cache, forward_with_cache, sample_token
from ..parallel import build_mesh, DATA_AXIS, MODEL_AXIS
from ..parallel.sharding import param_partition_specs, named
from ..utils.logging import log_dist

DTYPES = {"float16": jnp.float16, "bfloat16": jnp.bfloat16, "float32": jnp.float32}


def lru_compiled(cache, key, build, cap, label):
    """LRU lookup in ``cache`` (an OrderedDict) of the compiled program(s)
    for ``key``; ``build()`` compiles on miss. Over ``cap`` entries, the
    least-recently-used programs are evicted with a one-line warning —
    adversarial key mixes (e.g. prompt lengths) can't grow compiled programs
    without bound. Shared by the generate cache and the serving prefill
    cache."""
    if key in cache:
        cache.move_to_end(key)
        return cache[key]
    fns = build()
    cache[key] = fns
    if cap > 0 and len(cache) > cap:
        import logging

        evicted, _ = cache.popitem(last=False)
        log_dist(f"{label} compile cache over cap ({cap}): evicted programs "
                 f"for key {evicted}", ranks=[0], level=logging.WARNING)
    return fns


class InferenceEngine:
    def __init__(self, model, config, mesh=None, model_parameters=None):
        if model is None:
            raise ConfigError("init_inference: model is required")
        self.module = model
        self._config = config
        self.dtype = DTYPES[config.dtype]
        if hasattr(model, "config") and hasattr(model.config, "compute_dtype"):
            model.config.compute_dtype = self.dtype

        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        ep = config.moe.ep_size if config.moe.enabled else 1
        if ep > 1 and getattr(getattr(model, "config", None), "n_experts", 0) < 1:
            raise ConfigError(
                f"moe.ep_size={ep} needs an MoE model (n_experts > 0)")
        self.mesh = mesh if mesh is not None else build_mesh(
            MeshConfig(model=tp, expert=ep))
        self.mp_world_size = self.mesh.shape.get(MODEL_AXIS, 1)
        # the MoE dispatch constraints (moe/sharded_moe.py _expert_a2a) and
        # ring attention read the mesh off the model config
        if hasattr(model, "config") and hasattr(model.config, "mesh"):
            model.config.mesh = self.mesh
        if self.mp_world_size > 1 and hasattr(model, "config") \
                and getattr(model.config, "fused_qkv", False):
            # sharded-concat SPMD hazard (see runtime/engine.py): the fused
            # qkv concat is miscompiled when the kernels carry a model-axis
            # sharding; per-projection matmuls are bitwise per output column
            model.config.fused_qkv = False

        self._rng = jax.random.PRNGKey(config.seed)
        self._request_seq = 0  # folded into per-call rng: two requests with
        # the same prompt length must not share a sampling stream
        self._init_parameters(model_parameters)

        self._prefill_fn = None
        self._decode_fn = None
        # LRU of compiled (prefill, decode) pairs keyed by (batch, prompt
        # bucket, sampling shape); bounded by config.compile_cache_size so an
        # adversarial length mix can't grow compiled programs without bound
        self._prefill_cache = OrderedDict()
        self._serving = None

        log_dist(
            f"InferenceEngine: mesh={dict(self.mesh.shape)} dtype={config.dtype} "
            f"max_tokens={config.max_tokens}",
            ranks=[0],
        )

    # ------------------------------------------------------------------------------
    def _init_parameters(self, model_parameters):
        # unknown models (no Param axes metadata) default every leaf to
        # replicated — the injection_policy below is how users TP-place them
        if model_parameters is not None:
            if isinstance(model_parameters, tuple) and len(model_parameters) == 2:
                values, axes = model_parameters
            else:
                values, axes = split_params_axes(model_parameters)
        else:
            params_shape = jax.eval_shape(self.module.init, self._rng)
            axes = jax.tree_util.tree_map(
                lambda p: p.axes if isinstance(p, Param)
                else (None,) * len(p.shape),
                params_shape, is_leaf=lambda x: isinstance(x, Param))
            values = None

        if values is not None:
            shapes = jax.tree_util.tree_map(lambda v: tuple(v.shape), values)
        else:
            shapes = jax.tree_util.tree_map(
                lambda p: tuple((p.value if isinstance(p, Param) else p).shape),
                params_shape, is_leaf=lambda x: isinstance(x, Param))

        if self._config.injection_policy:
            from ..module_inject.policy import apply_injection_policy

            if self.mesh.shape.get(MODEL_AXIS, 1) <= 1:
                raise ConfigError(
                    "injection_policy given but tensor_parallel.tp_size is 1 "
                    "— the policy would silently serve a replicated model; "
                    "set tensor_parallel={'enabled': True, 'tp_size': N}")
            axes = apply_injection_policy(
                self._config.injection_policy, axes, shapes)

        # inference keeps params in the serving dtype (no fp32 masters) and TP-only
        # sharding (zero_stage=0: no data-sharded params)
        self.param_specs = param_partition_specs(axes, shapes, self.mesh, zero_stage=0)
        self.param_shardings = named(self.mesh, self.param_specs)

        if values is None:
            init_fn = lambda rng: jax.tree_util.tree_map(
                lambda a: (a.value if isinstance(a, Param) else a)
                .astype(self.dtype),
                self.module.init(rng), is_leaf=lambda x: isinstance(x, Param))
            with self.mesh:
                self.params = jax.jit(init_fn, out_shardings=self.param_shardings)(self._rng)
        else:
            self.params = jax.tree_util.tree_map(
                lambda v, s: jax.device_put(jnp.asarray(v, self.dtype), s),
                values, self.param_shardings)
        if self._config.quant.enabled:
            self._quantize_weights()

    def _quantize_weights(self):
        """int8 weight-only serving (reference ``replace_module.py:140``
        GroupQuantizer + the inference dequant kernels): every block matmul
        kernel becomes {kernel_q int8, kernel_scale} — the model reads weights
        from HBM at 8 bits and dequantizes inside the fused matmul
        (``models/layers.py linear_apply``)."""
        from ..models.layers import set_quantized_matmul_enabled
        from ..ops.quantizer import quantize_per_channel

        # the Pallas dequant-matmul has no sharding rule: under tp > 1 the
        # SPMD partitioner would replicate the model-axis-sharded quantized
        # weight per device, erasing the HBM win — keep the XLA dequant path
        # (which partitions correctly) for tensor-parallel serving
        tp = self._config.tensor_parallel.tp_size \
            if self._config.tensor_parallel.enabled else 1
        set_quantized_matmul_enabled(tp <= 1)
        bits = self._config.quant.bits
        group_size = self._config.quant.group_size
        counts = {"packed": 0, "int8": 0}

        def walk(tree, shardings, name=""):
            if isinstance(tree, dict):
                if "router" in name:
                    return tree  # MoE router must stay fp32 (stable gating)
                if "kernel" in tree and getattr(tree["kernel"], "ndim", 0) >= 2:
                    q, scale = quantize_per_channel(tree["kernel"], bits=bits,
                                                    group_size=group_size)
                    out = {k: v for k, v in tree.items() if k != "kernel"}
                    sh = shardings["kernel"]
                    if bits == 4 and q.shape[-2] % 2 == 0:
                        from ..ops.quantizer import pack_int4

                        # nibble-packed: 4 bits/weight in HBM
                        out["kernel_q4"] = jax.device_put(pack_int4(q), sh)
                        counts["packed"] += 1
                    else:
                        out["kernel_q"] = jax.device_put(q, sh)
                        counts["int8"] += 1
                    out["kernel_scale"] = scale
                    return out
                return {k: walk(v, shardings[k], f"{name}/{k}")
                        for k, v in tree.items()}
            return tree

        # only block matmuls; embeddings/norms stay in the serving dtype
        if not (isinstance(self.params, dict) and "blocks" in self.params):
            raise ConfigError(
                "quant.enabled needs a zoo-style model (params with a "
                "'blocks' subtree whose matmuls read quantized kernels); an "
                "injection-policy-served unknown model must be served "
                "unquantized")
        self.params = dict(self.params)
        self.params["blocks"] = walk(self.params["blocks"],
                                     self.param_shardings["blocks"])
        packed_note = f", {counts['packed']} nibble-packed" \
            if counts["packed"] else ""
        fallback_note = f", {counts['int8']} int8-stored" \
            if bits == 4 and counts["int8"] else ""
        log_dist(f"int{bits} weight-only quantization applied to "
                 f"{sum(counts.values())} block kernels "
                 f"(group_size={group_size}{packed_note}{fallback_note})",
                 ranks=[0])

    def load_checkpoint(self, load_dir, tag=None):
        """Load trained weights (npz layout from the training engine); TP
        resharding is just placement per the inference specs."""
        import os

        from ..checkpoint.sharded import ShardedCheckpointEngine

        if tag is None:
            latest = os.path.join(load_dir, "latest")
            tag = open(latest).read().strip() if os.path.exists(latest) else None
        path = os.path.join(load_dir, tag) if tag else load_dir
        # the checkpoint holds FULL-PRECISION weights: build the template from
        # the model's init shapes, not self.params (which may already be
        # int8-quantized with kernel_q/kernel_scale keys the manifest lacks)
        template = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.value.shape, self.dtype),
            jax.eval_shape(self.module.init, self._rng),
            is_leaf=lambda x: isinstance(x, Param))
        # sharded engine reads both layouts (per-shard pieces OR legacy npz)
        # and reshapes to the serving TP specs on load
        state, _ = ShardedCheckpointEngine().load(
            path, template={"params": template},
            shardings={"params": self.param_shardings})
        self.params = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(jnp.asarray(v, self.dtype), s),
            state["params"], self.param_shardings)
        if self._config.quant.enabled:
            self._quantize_weights()
        return path

    # ------------------------------------------------------------------------------
    # forward / generate (reference engine.forward :560, patched _generate :588)
    # ------------------------------------------------------------------------------
    def forward(self, input_ids):
        """Full-sequence logits (no cache) — scoring/perplexity path.

        Causal models bucket the sequence dim (right padding cannot reach
        earlier positions under a causal mask), so varying scoring lengths
        share compiled programs; the pad columns are sliced off."""
        input_ids = jnp.asarray(input_ids)
        b, s = input_ids.shape
        # no config = unknown model: don't assume causality — right-padding a
        # bidirectional model would let pad tokens attend into real positions
        # and silently corrupt the logits (skipping the bucket only costs one
        # compile per distinct length)
        mod_cfg = getattr(self.module, "config", None)
        causal = getattr(mod_cfg, "causal", True) if mod_cfg is not None \
            else False
        padded = s
        if causal:
            padded = self._bucket_prompt_len(s, self._config.max_tokens)
            if padded > s:
                input_ids = jnp.pad(input_ids, ((0, 0), (0, padded - s)))
        if self._prefill_fn is None:
            with self.mesh:
                self._prefill_fn = jax.jit(
                    lambda p, ids: self.module.apply(p, ids))
        logits = self._prefill_fn(self.params, input_ids)
        return logits[:, :s] if padded > s else logits

    def __call__(self, input_ids):
        return self.forward(input_ids)

    def destroy(self):
        """Release device memory and compiled programs (reference
        engine.py:381 role). Jitted prefill/decode closures capture ``self``;
        without this, dropping the engine leaves a gc cycle pinning the
        weights in HBM until a full collection happens to run."""
        self.params = None
        self._prefill_fn = None
        self._decode_fn = None
        self._prefill_cache = OrderedDict()
        if self._serving is not None:
            self._serving.destroy()
            self._serving = None
        import gc

        # no jax.clear_caches(): process-global, would wipe other live
        # engines' compiled programs; dropping our wrappers is enough
        gc.collect()

    def _bucket_prompt_len(self, prompt_len, ceiling):
        """Padded prompt length for ``prompt_len`` under the configured bucket
        policy, clipped to ``ceiling`` (the KV window minus generation room).

        "multiple": next multiple of prompt_bucket_size. "pow2" (default):
        next prompt_bucket_size doubling — at most log2(max_tokens) distinct
        buckets, so together with the LRU cap below the compiled-program set
        is bounded no matter what length mix arrives."""
        bucket = max(int(self._config.prompt_bucket_size), 1)
        if bucket > 1 and self._config.prompt_bucket_policy == "pow2":
            padded = bucket
            while padded < prompt_len:
                padded *= 2
        else:
            padded = -(-prompt_len // bucket) * bucket
        return max(min(padded, ceiling), prompt_len)

    def _compiled_programs(self, key, build):
        """LRU-bounded (prefill, decode) pair for ``key`` = (batch, prompt
        bucket, sampling shape)."""
        return lru_compiled(self._prefill_cache, key, build,
                            int(self._config.compile_cache_size or 0),
                            "inference")

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 greedy=True, eos_token_id=None, rng=None):
        """Autoregressive generation with a jitted prefill + decode loop.

        input_ids: [b, prompt_len] (uniform length; pad+mask generation is the
        serving layer's job, as in the reference's simple generate patching).
        Returns [b, prompt_len + max_new_tokens] int32.
        """
        if not hasattr(self.module, "config"):
            raise ConfigError(
                "generate() needs a zoo-style model (config with kv cache "
                "geometry + prefill/decode methods); an injection-policy-"
                "served unknown model supports forward() scoring only")
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, prompt_len = input_ids.shape
        max_len = prompt_len + max_new_tokens
        if max_len > self._config.max_tokens:
            raise ConfigError(
                f"generate: prompt {prompt_len} + max_new_tokens {max_new_tokens} "
                f"exceeds max_tokens {self._config.max_tokens}")
        # per-request rng: fold a monotonically increasing request id into the
        # engine key (two same-length requests must not share a stream); pass
        # an explicit ``rng`` for reproducible sampling
        self._request_seq += 1
        if rng is None:
            rng = jax.random.fold_in(
                jax.random.fold_in(self._rng, self._request_seq), prompt_len)

        # cache [L, b, max_len, kvh, dh]: batch over data, kv heads over model
        # (only when divisible — MQA/GQA may have fewer kv heads than tp)
        kvh = self.module.config.kv_heads
        kv_axis = MODEL_AXIS if kvh % max(self.mp_world_size, 1) == 0 else None
        batch_axis = DATA_AXIS if b % max(self.mesh.shape.get(DATA_AXIS, 1), 1) == 0 else None
        cache_sharding = NamedSharding(
            self.mesh, P(None, batch_axis, None, kv_axis, None))
        token_sharding = NamedSharding(self.mesh, P(batch_axis))

        # temperature is a RUNTIME argument (a sampling-knob change must not
        # recompile — the CUDA reference takes it per call too); greedy/top_k
        # shape the program and stay in the key. A concrete temperature of 0.0
        # IS greedy (and must stay exact argmax, not logits/1e-6 + noise).
        if isinstance(temperature, (int, float)) and temperature == 0.0:
            greedy = True

        # Batch-size BUCKETING (opt-in): pad the row dim to the next bucket by
        # repeating row 0 (garbage rows decode too; their outputs are dropped)
        # so varying request batch sizes share compiled programs.
        b_real = b
        b_bucket = max(int(self._config.batch_bucket_size), 1)
        if b % b_bucket:
            padded_b = -(-b // b_bucket) * b_bucket
            input_ids = jnp.concatenate(
                [input_ids,
                 jnp.broadcast_to(input_ids[:1],
                                  (padded_b - b,) + input_ids.shape[1:])])
            b = padded_b

        # Prompt-length BUCKETING: right-pad the prompt to the next bucket and
        # pass the true length as a traced scalar, so a TTFT-critical serving
        # loop compiles once per bucket, not once per distinct prompt length.
        padded_len = self._bucket_prompt_len(
            prompt_len, self._config.max_tokens - max_new_tokens)
        max_len = padded_len + max_new_tokens
        if padded_len > prompt_len:
            ids_in = jnp.pad(input_ids, ((0, 0), (0, padded_len - prompt_len)))
        else:
            ids_in = input_ids
        true_len = jnp.asarray(prompt_len, jnp.int32)

        key = (b, padded_len, max_new_tokens, bool(greedy), int(top_k),
               eos_token_id)

        def build():
            from ..models.decoding import (decode_tokens, decode_tokens_until,
                                           prefill_and_first_token)

            model = self.module

            def prefill(params, ids, rng, temperature, true_len):
                return prefill_and_first_token(
                    model, params, ids, rng, temperature, max_len=max_len,
                    greedy=greedy, top_k=top_k, dtype=self.dtype,
                    true_len=true_len)

            def decode(params, cache, tok, rng, temperature, true_len):
                if eos_token_id is not None:
                    # early exit inside the compiled loop once every row hit eos
                    return decode_tokens_until(
                        model, params, cache, tok, rng, temperature,
                        prompt_len=true_len, max_len=max_len,
                        steps=max_new_tokens - 1, greedy=greedy, top_k=top_k,
                        eos_token_id=int(eos_token_id))
                return decode_tokens(
                    model, params, cache, tok, rng, temperature,
                    prompt_len=true_len, max_len=max_len,
                    steps=max_new_tokens - 1, greedy=greedy, top_k=top_k)

            with self.mesh:
                return (
                    jax.jit(prefill,
                            out_shardings=(token_sharding,
                                           {"k": cache_sharding, "v": cache_sharding})),
                    jax.jit(decode, donate_argnums=(1,)),
                )

        prefill_fn, decode_fn = self._compiled_programs(key, build)
        rng, r1, r2 = jax.random.split(rng, 3)
        temp = jnp.asarray(temperature, jnp.float32)
        first, cache = prefill_fn(self.params, ids_in, r1, temp, true_len)
        out = [input_ids, first[:, None]]
        if max_new_tokens > 1:
            # the final cache is dropped, but returning it from the jitted fn
            # lets the donated input cache alias the output (no entry copy)
            toks, _ = decode_fn(self.params, cache, first, r2, temp, true_len)
            out.append(jnp.transpose(toks))
        result = jnp.concatenate(out, axis=1)
        if b_real < b:
            result = result[:b_real]
        if eos_token_id is not None:
            result = _truncate_after_eos(np.asarray(result), prompt_len, eos_token_id)
        return result

    def warmup(self, prompt_lens, max_new_tokens=32, batch_size=1,
               temperature=1.0, top_k=0, greedy=True, eos_token_id=None):
        """Precompile (and execute once) the prefill + decode programs for the
        given prompt lengths, so no live request ever pays a compile — the
        reference's capture-at-init role (cuda-graph capture on first forward,
        ``inference/engine.py:500``). Lengths collapse into prompt buckets;
        pass the production sampling shape (greedy/top_k/eos), since those
        are part of the compile key. Returns the number of compiled programs.
        """
        rng = np.random.RandomState(0)
        for p in prompt_lens:
            ids = rng.randint(0, self.module.config.vocab_size,
                              (batch_size, int(p))).astype(np.int32)
            self.generate(ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, top_k=top_k, greedy=greedy,
                          eos_token_id=eos_token_id)
        return len(self._prefill_cache)

    def serve(self, requests=None, **kwargs):
        """Continuous-batching streaming serving: yields per-request
        ``TokenEvent``s as tokens are produced (``serving/engine.py``). One
        jitted decode program over a fixed slot pool; finished requests free
        their slot mid-flight and queued ones are spliced in — no
        recompilation, no waiting for the batch to drain. Configure via the
        inference config's ``serving`` block."""
        return self.serving.serve(requests, **kwargs)

    @property
    def serving(self):
        """The lazily-built ServingEngine bound to this engine's weights."""
        if self._serving is None:
            from ..serving import ServingEngine

            self._serving = ServingEngine(self)
        return self._serving

    def decode_program_report(self, loop_trip_count=1):
        """Static audit of the serving decode program: collective wire bytes,
        schedule split, AND the program-sanitizer findings (dtype leaks,
        donation coverage of the slot-pool state, host transfers, replicated
        tensors, peak-HBM estimate) — the serving-side analogue of
        ``DeepSpeedEngine.collective_wire_stats``. Triggers one audit
        compile of the decode step (pass-dump pipeline, compilation cache
        off for that compile)."""
        from ..profiling.collectives import audit_lowered
        from ..profiling.sanitizer import (ATTENTION_F32_ALLOW,
                                           merge_reports, sanitize_jaxpr)

        sv = self.serving
        dtype = {jnp.bfloat16: "bf16", jnp.float16: "f16"}.get(
            self.dtype, "f32")
        cfg = {"compute_dtype": dtype, "allow": list(ATTENTION_F32_ALLOW)}
        n = max(self.mesh.devices.size, 1)
        lowered, jaxpr = sv.trace_decode()
        report = audit_lowered(lowered, n, loop_trip_count=loop_trip_count,
                               sanitizer_config=cfg)
        if jaxpr is not None:
            report["sanitizer"] = merge_reports(
                report["sanitizer"], sanitize_jaxpr(jaxpr, config=cfg))
        return report

    def prefill_chunk_report(self, chunk_tokens=None):
        """Static audit of the chunked suffix-prefill program (one full
        chunk's bucket against a donated partial cache) — the serving-side
        fence for chunked prefill, enforced via the
        ``serving-prefill-chunked/8/bf16`` budget
        (``tools/program_lint.py --program prefill-chunked``)."""
        from ..profiling.collectives import audit_lowered
        from ..profiling.sanitizer import (ATTENTION_F32_ALLOW,
                                           merge_reports, sanitize_jaxpr)

        sv = self.serving
        dtype = {jnp.bfloat16: "bf16", jnp.float16: "f16"}.get(
            self.dtype, "f32")
        cfg = {"compute_dtype": dtype, "allow": list(ATTENTION_F32_ALLOW)}
        n = max(self.mesh.devices.size, 1)
        lowered, jaxpr = sv.trace_prefill_chunk(chunk_tokens)
        report = audit_lowered(lowered, n, sanitizer_config=cfg)
        if jaxpr is not None:
            report["sanitizer"] = merge_reports(
                report["sanitizer"], sanitize_jaxpr(jaxpr, config=cfg))
        return report

    def verify_program_report(self, spec_k=None):
        """Static audit of the speculative verify program (one target
        forward over k+1 positions per slot against the donated paged pool
        state) — the serving-side fence for speculative decoding, enforced
        via the ``serving-verify/8/bf16`` budget
        (``tools/program_lint.py --program verify``)."""
        from ..profiling.collectives import audit_lowered
        from ..profiling.sanitizer import (ATTENTION_F32_ALLOW,
                                           merge_reports, sanitize_jaxpr)

        sv = self.serving
        dtype = {jnp.bfloat16: "bf16", jnp.float16: "f16"}.get(
            self.dtype, "f32")
        cfg = {"compute_dtype": dtype, "allow": list(ATTENTION_F32_ALLOW)}
        n = max(self.mesh.devices.size, 1)
        lowered, jaxpr = sv.trace_verify(spec_k)
        report = audit_lowered(lowered, n, sanitizer_config=cfg)
        if jaxpr is not None:
            report["sanitizer"] = merge_reports(
                report["sanitizer"], sanitize_jaxpr(jaxpr, config=cfg))
        return report

    @property
    def config(self):
        return self._config


def _truncate_after_eos(tokens, prompt_len, eos):
    """Replace everything after the first EOS (per row) with EOS."""
    tokens = tokens.copy()
    gen = tokens[:, prompt_len:]
    for row in range(gen.shape[0]):
        hits = np.where(gen[row] == eos)[0]
        if hits.size:
            gen[row, hits[0]:] = eos
    tokens[:, prompt_len:] = gen
    return tokens
