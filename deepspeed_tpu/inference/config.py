"""Inference config (reference ``inference/config.py`` DeepSpeedInferenceConfig).

Same key surface where it maps to TPU: dtype, tensor_parallel, max_tokens,
quantization; CUDA-graph flags disappear (a jitted decode step IS the captured
graph), kernel-injection flags disappear (XLA fuses the inference kernels).
"""

import typing

from ..config.base import ConfigModel
from ..config.config import (CSVConfig, HealthConfig, ServingConfig,
                             TelemetryConfig, TensorBoardConfig, WandbConfig)


class TensorParallelConfig(ConfigModel):
    """Reference ``inference/config.py`` DeepSpeedTPConfig."""

    enabled: bool = True
    tp_size: int = 1


class MoEInferenceConfig(ConfigModel):
    """Reference ``inference/config.py`` DeepSpeedMoEConfig (``ep_size``):
    expert-parallel serving — experts shard over the ``expert`` mesh axis and
    token dispatch rides the same all_to_all constraints as training."""

    enabled: bool = True
    ep_size: int = 1


class QuantizationConfig(ConfigModel):
    """Weight quantization (reference ``replace_module.py:140`` GroupQuantizer)."""

    enabled: bool = False
    bits: int = 8
    group_size: int = 64


class DeepSpeedInferenceConfig(ConfigModel):
    dtype: str = "bfloat16"
    tensor_parallel: TensorParallelConfig = None
    max_tokens: int = 1024          # reference max_out_tokens
    min_tokens: int = 1
    max_batch_size: int = 8
    # generate() pads prompts up to a multiple of this, so serving compiles one
    # program per LENGTH BUCKET instead of one per distinct prompt length
    # (recompile-free TTFT for varying prompts). 1 disables bucketing.
    prompt_bucket_size: int = 64
    # "pow2": buckets are prompt_bucket_size doublings (16, 32, 64, ...), so
    # an adversarial prompt-length mix compiles at most log2(max_tokens)
    # programs. "multiple": every multiple of prompt_bucket_size is a bucket
    # (tighter padding, unbounded distinct buckets).
    prompt_bucket_policy: str = "pow2"
    # LRU cap on compiled prefill/decode program pairs; evicting logs one
    # warning line. 0 = unbounded.
    compile_cache_size: int = 32
    # generate() pads the BATCH dim up to a multiple of this (padded rows are
    # dropped from the output). 1 disables; opt in when request batch sizes
    # vary — row padding costs compute but saves the recompile.
    batch_bucket_size: int = 1
    # continuous-batching serving layer (serving/engine.py ServingEngine)
    serving: ServingConfig = None
    # serving metrics backends (Serving/* events; same sections as training)
    tensorboard: TensorBoardConfig = None
    wandb: WandbConfig = None
    csv_monitor: CSVConfig = None
    # span tracing of serving request lifecycles (queued -> prefill ->
    # first token -> decode steps -> finish/shed); same block as training
    telemetry: TelemetryConfig = None
    # numerics watchdog for the serving loop: enabled arms the in-graph
    # nonfinite-logit count's consumers (Serving/health_* events + the
    # unhealthy_slot shed); same block shape as training
    health: HealthConfig = None
    quant: QuantizationConfig = None
    moe: MoEInferenceConfig = None
    replace_with_kernel_inject: bool = False  # accepted for config compat; no-op
    # reference mode-1 user injection policy (inference/engine.py:190), as
    # {path_regex: "column"|"row"|"replicate"|axes_tuple} — see
    # module_inject/policy.py
    injection_policy: typing.Any = None
    seed: int = 0

    def _validate(self):
        if self.tensor_parallel is None:
            self.tensor_parallel = TensorParallelConfig()
        if self.quant is None:
            self.quant = QuantizationConfig()
        if self.moe is None:
            self.moe = MoEInferenceConfig()
        if self.serving is None:
            self.serving = ServingConfig()
        if self.tensorboard is None:
            self.tensorboard = TensorBoardConfig()
        if self.wandb is None:
            self.wandb = WandbConfig()
        if self.csv_monitor is None:
            self.csv_monitor = CSVConfig()
        if self.telemetry is None:
            self.telemetry = TelemetryConfig()
        if self.health is None:
            self.health = HealthConfig()
        from ..config.base import ConfigError

        if self.dtype not in ("float16", "bfloat16", "float32"):
            raise ConfigError(f"inference dtype must be fp16/bf16/fp32, got {self.dtype}")
        if self.prompt_bucket_policy not in ("pow2", "multiple"):
            raise ConfigError(
                "prompt_bucket_policy must be 'pow2' or 'multiple', got "
                f"{self.prompt_bucket_policy!r}")
