"""``deepspeed_tpu.pipe`` — the reference's ``deepspeed.pipe`` namespace
(``deepspeed/pipe/__init__.py``): pipeline-parallel training over user module
lists. See ``parallel/pipeline_module.py`` for the TPU design."""

from .parallel.pipeline_module import (  # noqa: F401
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
    partition_balanced,
)

__all__ = ["LayerSpec", "PipelineModule", "TiedLayerSpec", "partition_balanced"]
