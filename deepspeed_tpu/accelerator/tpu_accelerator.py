"""The TPU (and virtual-CPU-mesh) accelerator implementation.

Reference counterpart: ``accelerator/cuda_accelerator.py`` (``CUDA_Accelerator``)
— one concrete class retargets the whole stack. ``communication_backend_name``
is what ``comm.init_distributed`` brings up (the reference returns 'nccl'
there; here the collectives ride XLA over ICI/DCN via ``jax.distributed``)."""

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    name = "tpu"

    def devices(self):
        import jax

        return jax.devices()

    def device_count(self):
        return len(self.devices())

    def current_device(self):
        return self.devices()[0]

    def device_name(self, device_index=None):
        d = self.devices()[device_index or 0]
        return getattr(d, "device_kind", str(d))

    def memory_stats(self, device_index=None):
        d = self.devices()[device_index or 0]
        stats = getattr(d, "memory_stats", lambda: None)()
        return dict(stats) if stats else {}

    def is_fp64_supported(self):
        import jax

        return bool(jax.config.jax_enable_x64) and \
            self.devices()[0].platform == "cpu"

    def communication_backend_name(self):
        return "xla"  # jax.distributed + XLA collectives (ICI/DCN)

    def op_builder(self, name):
        from ..ops.op_builder import ALL_OPS

        return ALL_OPS.get(name)
