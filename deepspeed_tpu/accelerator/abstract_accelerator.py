"""Accelerator abstraction: the pluggable-platform seam.

TPU-native counterpart of the reference's ``accelerator/abstract_accelerator.py``
(``DeepSpeedAccelerator`` ABC, ~60 methods) + ``real_accelerator.py`` selection
logic. The reference uses this seam to retarget torch code across CUDA/XPU/CPU;
here the compute API is JAX itself (device placement, RNG, and streams are
jax-level concepts), so the abstraction carries what a second backend would
actually need to swap:

- device enumeration / selection / properties,
- memory statistics and empty-cache semantics,
- dtype capability flags (bf16/fp16/fp64),
- the communication-backend name the comm layer initializes,
- synchronization (the "stream" surface collapses to ``block_until_ready`` —
  XLA programs are the streams),
- op-builder dispatch (which native extensions exist and how to build them),
- RNG seeding helpers.

``get_accelerator()`` returns the process-wide accelerator;
``set_accelerator()`` registers an out-of-tree implementation before first use
(the reference's ``set_accelerator`` contract, ``real_accelerator.py:55``).
"""

import abc


class DeepSpeedAccelerator(abc.ABC):
    """Capability surface a backend must provide (subset of the reference ABC
    that is meaningful under a compiled-XLA execution model; the stream/event
    and tensor-factory groups collapse — see class docstring)."""

    name: str = ""

    # ---- device management (reference :18-42) --------------------------------
    @abc.abstractmethod
    def devices(self):
        """All addressable accelerator devices (jax.Device list)."""

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        """Default device for uncommitted arrays."""

    @abc.abstractmethod
    def device_name(self, device_index=None):
        """Human-readable device kind (e.g. 'TPU v5e')."""

    def is_available(self):
        return self.device_count() > 0

    # ---- synchronization (reference Streams/Events :77-94) -------------------
    def synchronize(self, x=None):
        """Block until outstanding work on ``x`` (or everything) finishes.
        Streams/events have no user-level analog under XLA: each compiled
        program is an ordered stream; donation expresses the dependencies."""
        import jax

        if x is not None:
            return jax.block_until_ready(x)
        for d in self.devices():
            try:
                d.synchronize_all_activity()
            except AttributeError:
                pass
        return None

    # ---- memory (reference :99-143) ------------------------------------------
    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        """dict with at least bytes_in_use / bytes_limit when the platform
        reports them (empty dict otherwise)."""

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self.memory_stats(device_index)
        return max(0, s.get("bytes_limit", 0) - s.get("bytes_in_use", 0))

    def empty_cache(self):
        """XLA owns the allocator; live buffers are freed by dropping
        references (donation in-program). No-op hook for API parity."""

    # ---- dtype capabilities (reference :148-161) -----------------------------
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def is_fp64_supported(self):
        return False

    # ---- RNG (reference :47-71) ----------------------------------------------
    def manual_seed(self, seed):
        import jax

        return jax.random.PRNGKey(seed)

    # ---- communication backend (reference :177) ------------------------------
    @abc.abstractmethod
    def communication_backend_name(self):
        """What comm.init_distributed initializes over."""

    # ---- op builders (reference :225-239) ------------------------------------
    @abc.abstractmethod
    def op_builder(self, name):
        """Return the OpBuilder class for a named native op, or None."""

    def create_op_builder(self, name):
        cls = self.op_builder(name)
        return cls() if cls is not None else None
