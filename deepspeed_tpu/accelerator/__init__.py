"""Accelerator selection (reference ``accelerator/real_accelerator.py:37-55``)."""

from .abstract_accelerator import DeepSpeedAccelerator  # noqa: F401
from .tpu_accelerator import TPU_Accelerator  # noqa: F401

_accelerator = None


def get_accelerator():
    global _accelerator
    if _accelerator is None:
        _accelerator = TPU_Accelerator()
    return _accelerator


def set_accelerator(accel):
    """Register an out-of-tree accelerator BEFORE first use (the reference
    raises on late registration too)."""
    global _accelerator
    if _accelerator is not None and _accelerator is not accel:
        raise RuntimeError(
            "set_accelerator called after get_accelerator; register the "
            "backend before any framework component touches the platform")
    _accelerator = accel


__all__ = ["DeepSpeedAccelerator", "TPU_Accelerator", "get_accelerator",
           "set_accelerator"]
