"""``deepspeed_tpu.zero`` — user-facing ZeRO helpers (reference
``deepspeed.zero`` surface: ``Init`` context, ``GatheredParameters``).

The heavy machinery behind the reference names does not exist here because the
sharding design makes it unnecessary — these are the thin, real equivalents:

- ``zero.Init``: in the reference, a monkey-patching context that partitions
  params at module construction (``partition_parameters.py:601``). Here params
  are BORN sharded — ``initialize()`` traces ``model.init`` and materializes
  straight into the ZeRO layout — so ``Init`` is a no-op context kept for
  migration compatibility (wrapping model construction in it is harmless).
- ``zero.GatheredParameters``: host access to (possibly ZeRO-3/TP-sharded)
  params (reference ``partition_parameters.py:1500``). Enter gathers to a
  mutable numpy tree; with ``write_back=True``, exit re-places the (edited)
  tree into the original device shardings.
"""

import numpy as np

import jax


class Init:
    """No-op migration shim: params are born sharded (see module docstring)."""

    def __init__(self, *args, **kwargs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class GatheredParameters:
    """Gather engine params (or any jax-array pytree) to host for inspection
    or surgery.

    with zero.GatheredParameters(engine, write_back=True) as host:
        host["wte"]["weight"][0] = 0.0   # numpy, mutable
    # exit: edits are device_put back into the original shardings
    """

    def __init__(self, params_or_engine, write_back=False):
        self._engine = None
        if hasattr(params_or_engine, "params"):
            self._engine = params_or_engine
            self._params = params_or_engine.params
        else:
            self._params = params_or_engine
        self.write_back = write_back
        self._host = None

    def __enter__(self):
        self._host = jax.tree_util.tree_map(
            lambda a: np.array(jax.device_get(a)), self._params)
        return self._host

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self.write_back:
            placed = jax.tree_util.tree_map(
                lambda h, a: jax.device_put(
                    np.asarray(h, dtype=a.dtype), a.sharding),
                self._host, self._params)
            if self._engine is not None:
                self._engine.params = placed
            else:
                # caller holds the tree; mutate leaves in place is impossible
                # for jax arrays, so expose the result for pickup
                self.result = placed
        return False
