"""Experiment monitoring.

TPU-native equivalent of the reference's ``deepspeed/monitor/``: ``Monitor`` ABC +
``MonitorMaster`` fan-out (``monitor/monitor.py:13,:29``) over TensorBoard
(``tensorboard.py:13``), W&B (``wandb.py:12``) and CSV (``csv_monitor.py:12``)
backends; writes happen on process rank 0 only.
"""

import csv
import json
import os
import time

from .. import comm as dist
from ..utils.logging import logger


class Monitor:
    def __init__(self, config):
        self.config = config
        self.enabled = config.enabled

    def write_events(self, event_list):
        """event_list: [(name, value, step), ...]"""
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    """Reference ``monitor/tensorboard.py:13``. Uses torch's SummaryWriter if
    importable (torch-cpu is in the image); silently disables otherwise."""

    def __init__(self, config):
        super().__init__(config.tensorboard)
        self.summary_writer = None
        if self.enabled and dist.get_rank() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                base = config.tensorboard.output_path or "./runs"
                self.summary_writer = SummaryWriter(
                    log_dir=os.path.join(base, config.tensorboard.job_name)
                )
            except Exception as e:  # pragma: no cover
                logger.warning(f"TensorBoard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    """Reference ``monitor/wandb.py:12``."""

    def __init__(self, config):
        super().__init__(config.wandb)
        self._wandb = None
        if self.enabled and dist.get_rank() == 0:
            try:
                import wandb

                wandb.init(project=config.wandb.project, group=config.wandb.group or None,
                           entity=config.wandb.team or None)
                self._wandb = wandb
            except Exception as e:  # pragma: no cover
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class CSVMonitor(Monitor):
    """Reference ``monitor/csv_monitor.py:12``: one CSV file per metric name."""

    def __init__(self, config):
        super().__init__(config.csv_monitor)
        self.output_path = None
        if self.enabled and dist.get_rank() == 0:
            base = config.csv_monitor.output_path or "./csv_logs"
            self.output_path = os.path.join(base, config.csv_monitor.job_name)
            os.makedirs(self.output_path, exist_ok=True)

    def write_events(self, event_list):
        if self.output_path is None:
            return
        for name, value, step in event_list:
            fname = os.path.join(self.output_path, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class TraceFileMonitor(Monitor):
    """Trace-file backend: appends scalar events as JSONL next to the span
    trace (``<telemetry.output_path>/<job_name>/scalars.jsonl``), so the
    same directory holds spans AND the scalars recorded against them —
    ``tools/trace_summary.py`` joins both (e.g. flags steps whose
    ``Comm/exposed_frac`` exceeds budget). Gated on the ``telemetry``
    config block; rank 0 only."""

    def __init__(self, config):
        tel = getattr(config, "telemetry", None)
        # duck-typed stand-in for a config section: enabled + job fields
        self.config = tel
        self.enabled = bool(tel is not None and tel.enabled)
        self.path = None
        if self.enabled and dist.get_rank() == 0:
            base = tel.output_path or "./traces"
            d = os.path.join(base, tel.job_name)
            os.makedirs(d, exist_ok=True)
            self.path = os.path.join(d, "scalars.jsonl")
            # fresh run, fresh scalar stream (spans.jsonl does the same)
            open(self.path, "w").close()

    def write_events(self, event_list):
        if self.path is None:
            return
        now = time.time()
        with open(self.path, "a") as f:
            for name, value, step in event_list:
                f.write(json.dumps({"name": name, "value": float(value),
                                    "step": int(step), "time": now}) + "\n")


class MonitorMaster(Monitor):
    """Reference ``monitor/monitor.py:29``: fan out to all enabled backends.

    One failing backend (a TensorBoard/W&B import-or-IO error mid-run, a
    full disk under the CSV dir) must cost its own events, not the training
    step: each backend's write is isolated, and the first failure logs one
    warning naming the backend — later failures of the same backend are
    silent (a wedged writer at ``steps_per_print`` cadence would otherwise
    flood the log)."""

    def __init__(self, config):
        self.backends = [
            TensorBoardMonitor(config),
            WandbMonitor(config),
            CSVMonitor(config),
            TraceFileMonitor(config),
        ]
        self.enabled = any(b.enabled for b in self.backends)
        self._failed = set()

    def write_events(self, event_list):
        if not event_list or dist.get_rank() != 0:
            return
        for b in self.backends:
            if not b.enabled:
                continue
            try:
                b.write_events(event_list)
            except Exception as e:
                name = type(b).__name__
                if name not in self._failed:
                    self._failed.add(name)
                    logger.warning(
                        "monitor backend %s failed to write events (%s); "
                        "training continues, further %s failures are "
                        "suppressed", name, e, name)
