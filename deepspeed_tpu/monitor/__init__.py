from .monitor import (Monitor, MonitorMaster, TensorBoardMonitor,
                      WandbMonitor, CSVMonitor, TraceFileMonitor)

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CSVMonitor", "TraceFileMonitor"]
