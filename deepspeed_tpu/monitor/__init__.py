from .monitor import Monitor, MonitorMaster, TensorBoardMonitor, WandbMonitor, CSVMonitor

__all__ = ["Monitor", "MonitorMaster", "TensorBoardMonitor", "WandbMonitor", "CSVMonitor"]
