"""KV-cache decoding path for the transformer backbone.

TPU-native equivalent of the reference's inference kernels
(``csrc/transformer/inference/csrc/`` — fused "softmax_context" attention with
KV-cache, ``apply_rotary_pos_emb.cu``) and the ``DeepSpeedTransformerInference``
module (``model_implementations/transformers/ds_transformer.py:19``). The CUDA
version hand-manages a contiguous KV workspace; here the cache is a pytree of
``[layers, batch, max_len, kv_heads, head_dim]`` arrays updated with
``dynamic_update_slice`` inside a jitted decode step — XLA keeps the update
in-place through buffer donation.

Kept separate from the training path (``transformer.block_apply``) like the
reference keeps training vs inference kernels separate; a parity test pins
prefill logits == training-forward logits.
"""

import jax
import jax.numpy as jnp

from . import layers as L
from .transformer import _norm_apply


def init_cache(cfg, batch_size, max_len, dtype=None):
    """Allocate the KV cache: k/v stacked over layers (matches the stacked block
    params, so layer scan indexes both together)."""
    dtype = dtype or cfg.compute_dtype
    kvh = cfg.kv_heads
    shape = (cfg.n_layers, batch_size, max_len, kvh, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
    }


def _attn_with_cache(cfg, p_attn, h, k_cache, v_cache, pos, kv_len, rope=None,
                     is_local=None, prefill=False):
    """Attention for q block [b, q, d] against cache[:, :kv_len] after writing the
    new k/v at ``pos``. Returns (out [b, q, d], new k_cache, new v_cache).

    k_cache/v_cache: [b, max_len, kvh, dh]; pos: scalar write offset;
    kv_len: static upper bound on valid cache length (mask handles the rest).
    ``prefill``: static caller promise that pos == 0 and the q block IS the
    whole visible window — enables the flash fast path below.
    """
    b, q_len, d = h.shape
    q = L.linear_apply(p_attn["q"], h).reshape(b, q_len, cfg.n_heads, cfg.head_dim)
    k = L.linear_apply(p_attn["k"], h).reshape(b, q_len, cfg.kv_heads, cfg.head_dim)
    v = L.linear_apply(p_attn["v"], h).reshape(b, q_len, cfg.kv_heads, cfg.head_dim)
    if rope is not None:
        cos, sin = rope
        q = L.apply_rotary(q, cos, sin, cfg.rotary_dim,
                           cfg.rotary_interleaved)
        k = L.apply_rotary(k, cos, sin, cfg.rotary_dim,
                           cfg.rotary_interleaved)

    k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                           (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                           (0, pos, 0, 0))

    # Prefill is plain causal attention over the just-written prompt rows:
    # cache slot j >= q_len is in the causal future of every query, so the
    # [q, max_len] window the dense path masks away never needs to exist.
    # Route it through the flash kernel so TTFT doesn't pay the O(s^2)
    # logits materialization — on the fresh k/v (cast through the cache
    # dtype to keep the dense path's numerics), repeated BEFORE any cache
    # read so no [b, max_len, heads, dh] tensor materializes. prefill_flash:
    # True/False force, None = TPU backend only (the CPU fallback is the
    # chunked-XLA flash, correct everywhere).
    flash_wanted = cfg.prefill_flash
    if flash_wanted is None:
        flash_wanted = jax.default_backend() == "tpu"
    if (flash_wanted and prefill and q_len > 1 and is_local is None
            and cfg.position_embedding != "alibi"):
        from ..ops.flash_attention import flash_attention

        n_rep = cfg.n_heads // cfg.kv_heads
        out = flash_attention(q,
                              L._repeat_kv(k.astype(k_cache.dtype), n_rep),
                              L._repeat_kv(v.astype(v_cache.dtype), n_rep),
                              causal=True, scale=cfg.attn_scale,
                              block_q=cfg.flash_block_q,
                              block_kv=cfg.flash_block_kv)
        out = L.linear_apply(p_attn["o"], out.reshape(b, q_len, -1))
        return out, k_cache, v_cache

    k_full = L._repeat_kv(k_cache[:, :kv_len], cfg.n_heads // cfg.kv_heads)
    v_full = L._repeat_kv(v_cache[:, :kv_len], cfg.n_heads // cfg.kv_heads)

    # causal vs the cache: query i (global pos+i) sees cache slots <= pos+i
    kv_idx = jnp.arange(kv_len)[None, :]
    q_idx = pos + jnp.arange(q_len)[:, None]
    allowed = kv_idx <= q_idx
    if cfg.local_attention_window > 0 and is_local is not None:
        # banded local layers (GPT-Neo): is_local is a traced per-layer bool
        band = q_idx - kv_idx < cfg.local_attention_window
        allowed = allowed & (band | jnp.logical_not(is_local))
    mask = allowed[None, None, :, :]  # [1, 1, q, kv]

    alibi = None
    if cfg.position_embedding == "alibi":
        alibi = _alibi_slice(cfg, q_len, kv_len, pos)

    out = L.dot_product_attention(
        q, k_full, v_full, mask=mask, scale=cfg.attn_scale, alibi_bias=alibi,
        # bf16 logits cut prefill TTFT's [b,h,s,s] HBM traffic too; decode
        # steps ([b,h,1,kv]) are unaffected either way
        logits_dtype=cfg.attn_logits_jnp_dtype)
    # -1, not d: head-pruned models have attention width n_heads*head_dim < d
    out = L.linear_apply(p_attn["o"], out.reshape(b, q_len, -1))
    return out, k_cache, v_cache


def _alibi_slice(cfg, q_len, kv_len, pos):
    """ALiBi bias for queries at global positions [pos, pos+q) vs keys [0, kv)."""
    full = L.alibi_bias(cfg.n_heads, kv_len, kv_len)  # [h|1xh, kv, kv] layout
    # L.alibi_bias returns [1, heads, q, kv]; slice the query rows
    return jax.lax.dynamic_slice_in_dim(full, pos, q_len, axis=2)


def _mlp(cfg, p, h):
    if cfg.n_experts > 0:
        from ..moe import moe_mlp_apply

        out, _ = moe_mlp_apply(cfg, p["mlp"], h, deterministic=True)
        return out
    act = L.ACTIVATIONS[cfg.activation] if cfg.activation != "swiglu" else None
    mp = jax.tree_util.tree_map(
        lambda a: a.astype(h.dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, p["mlp"])
    if cfg.activation == "swiglu":
        gate = L.linear_apply(mp["gate"], h)
        up = L.linear_apply(mp["up"], h)
        return L.linear_apply(mp["down"], jax.nn.silu(gate) * up)
    return L.linear_apply(mp["proj"], act(L.linear_apply(mp["fc"], h)))


def _block_cached(cfg, p, x, k_cache, v_cache, pos, kv_len, rope=None,
                  is_local=None, prefill=False):
    """One block with cache. x: [b, q, d] compute dtype."""
    cast = lambda a: a.astype(cfg.compute_dtype) \
        if jnp.issubdtype(a.dtype, jnp.floating) else a
    p_cast = {
        "ln_1": p["ln_1"],
        "ln_2": p["ln_2"],
        "attn": jax.tree_util.tree_map(cast, p["attn"]),
        "mlp": p["mlp"],
    }

    def attn(h):
        return _attn_with_cache(cfg, p_cast["attn"], h, k_cache, v_cache, pos,
                                kv_len, rope=rope, is_local=is_local,
                                prefill=prefill)

    if cfg.parallel_attn_mlp:
        h = _norm_apply(cfg, p_cast["ln_1"], x)
        h_mlp = _norm_apply(cfg, p_cast["ln_2"], x) \
            if cfg.parallel_norm_split else h
        a, kc, vc = attn(h)
        return x + a + _mlp(cfg, p_cast, h_mlp), kc, vc
    if cfg.prenorm:
        a, kc, vc = attn(_norm_apply(cfg, p_cast["ln_1"], x))
        x = x + a
        x = x + _mlp(cfg, p_cast, _norm_apply(cfg, p_cast["ln_2"], x))
        return x, kc, vc
    a, kc, vc = attn(x)
    x = _norm_apply(cfg, p_cast["ln_1"], x + a)
    x = _norm_apply(cfg, p_cast["ln_2"], x + _mlp(cfg, p_cast, x))
    return x, kc, vc


def forward_with_cache(model, params, input_ids, cache, pos, kv_len,
                       prefill=False):
    """Run the model on ``input_ids`` [b, q] writing k/v into ``cache`` at ``pos``.

    Used for both prefill (q = prompt length, pos = 0) and decode (q = 1,
    pos = cursor). Returns (logits [b, q, vocab], new_cache).
    ``prefill=True`` is the caller's static promise that pos == 0 and the
    whole visible window is this q block — it unlocks the flash fast path
    (callers with pos > 0 must leave it False).
    """
    cfg = model.config
    b, q_len = input_ids.shape
    positions = pos + jnp.arange(q_len)[None, :]
    positions = jnp.broadcast_to(positions, (b, q_len))

    x = L.embedding_apply(params["wte"], input_ids, cfg.compute_dtype)
    if cfg.position_embedding == "learned":
        x = x + jnp.take(params["wpe"]["weight"].astype(cfg.compute_dtype),
                         positions, axis=0)
    rope = None
    if cfg.position_embedding == "rope":
        rope = L.rotary_embedding(positions, cfg.rotary_dim or cfg.head_dim,
                                  cfg.rope_base)

    if cfg.local_attention_window > 0:
        from .transformer import local_attention_flags

        is_local_arr = jnp.asarray(local_attention_flags(cfg))

        def scan_fn(carry, layer):
            h = carry
            p_i, kc, vc, loc = layer
            h, kc, vc = _block_cached(cfg, p_i, h, kc, vc, pos, kv_len,
                                      rope=rope, is_local=loc,
                                      prefill=prefill)
            return h, (kc, vc)

        h, (k_new, v_new) = jax.lax.scan(
            scan_fn, x, (params["blocks"], cache["k"], cache["v"], is_local_arr)
        )
    else:
        def scan_fn(carry, layer):
            h = carry
            p_i, kc, vc = layer
            h, kc, vc = _block_cached(cfg, p_i, h, kc, vc, pos, kv_len,
                                      rope=rope, prefill=prefill)
            return h, (kc, vc)

        h, (k_new, v_new) = jax.lax.scan(
            scan_fn, x, (params["blocks"], cache["k"], cache["v"])
        )
    h = _norm_apply(cfg, params["ln_f"], h)
    if cfg.tie_embeddings:
        logits = L.embedding_attend(params["wte"], h)
    else:
        logits = L.linear_apply(params["lm_head"], h)
    return logits, {"k": k_new, "v": v_new}


def sample_token(logits, rng, *, temperature=1.0, top_k=0, greedy=False):
    """logits: [b, vocab] -> [b] int32.

    ``greedy`` and ``top_k`` are static (shape the program); ``temperature``
    may be a TRACED scalar so serving/rollout loops can change it without
    recompiling (the reference recompiles nothing — CUDA kernels take it as a
    runtime arg; so do we)."""
    logits = logits.astype(jnp.float32)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if isinstance(temperature, (int, float)) and temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def prefill_and_first_token(model, params, ids, rng, temperature, *, max_len,
                            greedy, top_k, dtype, true_len=None):
    """Prefill the KV cache with the prompt and sample the first new token.
    Shared by the serving engine and the hybrid (RLHF) engine — one
    implementation of the rollout math, two jit wrappers.

    ``true_len`` (traced scalar) supports right-padded bucketed prompts: the
    first token is sampled at column ``true_len - 1`` instead of the last
    column. Pad slots beyond ``true_len`` hold garbage k/v but always sit in
    the causally-masked future of every real query, and the decode loop
    overwrites each one exactly when its position enters the window — so no
    mask tensor is needed (the serving engine recompiles per prompt LENGTH
    BUCKET, not per length; cf. the reference re-using one CUDA workspace
    across lengths)."""
    b, prompt_len = ids.shape
    cache = init_cache(model.config, b, max_len, dtype)
    logits, cache = forward_with_cache(model, params, ids, cache, 0, max_len,
                                       prefill=True)
    if true_len is None:
        last = logits[:, prompt_len - 1]
    else:
        last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)[:, 0]
    tok = sample_token(last, rng, temperature=temperature,
                       top_k=top_k, greedy=greedy)
    return tok, cache


def decode_tokens(model, params, cache, tok, rng, temperature, *, prompt_len,
                  max_len, steps, greedy, top_k):
    """Scan ``steps`` single-token decode iterations.

    Returns ``(toks [steps, b], cache)``. The final cache is returned (even
    though callers usually drop it) so a caller that donates the input cache
    gives XLA an output to alias — otherwise the donation is unusable and the
    compiled program copies the cache at loop entry."""

    def step(carry, i):
        cache, tok, rng = carry
        rng, r = jax.random.split(rng)
        logits, cache = forward_with_cache(
            model, params, tok[:, None], cache, prompt_len + i, max_len)
        nxt = sample_token(logits[:, 0], r, temperature=temperature,
                           top_k=top_k, greedy=greedy)
        return (cache, nxt, rng), nxt

    (cache, _, _), toks = jax.lax.scan(step, (cache, tok, rng),
                                       jnp.arange(steps))
    return toks, cache


def decode_tokens_until(model, params, cache, tok, rng, temperature, *,
                        prompt_len, max_len, steps, greedy, top_k,
                        eos_token_id):
    """Early-stopping decode: a ``while_loop`` that exits as soon as EVERY row
    has emitted ``eos_token_id`` (the reference's generate-stops-at-eos
    behavior, but inside the compiled program — short answers don't pay for
    ``max_new_tokens`` iterations). Rows that finished keep emitting eos.
    Returns ``(out [steps, b], cache)`` (positions past a row's eos filled
    with eos; the cache is returned for donation aliasing, see
    ``decode_tokens``)."""
    b = tok.shape[0]
    out0 = jnp.full((steps, b), eos_token_id, jnp.int32)
    done0 = tok == eos_token_id

    def cond(carry):
        i, done, *_ = carry
        return jnp.logical_and(i < steps, jnp.logical_not(jnp.all(done)))

    def body(carry):
        i, done, cache, tok, rng, out = carry
        rng, r = jax.random.split(rng)
        logits, cache = forward_with_cache(
            model, params, tok[:, None], cache, prompt_len + i, max_len)
        nxt = sample_token(logits[:, 0], r, temperature=temperature,
                           top_k=top_k, greedy=greedy)
        nxt = jnp.where(done, jnp.asarray(eos_token_id, jnp.int32), nxt)
        out = out.at[i].set(nxt)
        done = jnp.logical_or(done, nxt == eos_token_id)
        return (i + 1, done, cache, nxt, rng, out)

    (_, _, cache, _, _, out) = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), done0, cache, tok, rng, out0))
    return out, cache
