"""KV-cache decoding path for the transformer backbone.

TPU-native equivalent of the reference's inference kernels
(``csrc/transformer/inference/csrc/`` — fused "softmax_context" attention with
KV-cache, ``apply_rotary_pos_emb.cu``) and the ``DeepSpeedTransformerInference``
module (``model_implementations/transformers/ds_transformer.py:19``). The CUDA
version hand-manages a contiguous KV workspace; here the cache is a pytree of
``[layers, batch, max_len, kv_heads, head_dim]`` arrays updated with
``dynamic_update_slice`` inside a jitted decode step — XLA keeps the update
in-place through buffer donation.

Kept separate from the training path (``transformer.block_apply``) like the
reference keeps training vs inference kernels separate; a parity test pins
prefill logits == training-forward logits.
"""

import jax
import jax.numpy as jnp

from . import layers as L
from .transformer import _norm_apply


def init_cache(cfg, batch_size, max_len, dtype=None):
    """Allocate the KV cache: k/v stacked over layers (matches the stacked block
    params, so layer scan indexes both together)."""
    dtype = dtype or cfg.compute_dtype
    kvh = cfg.kv_heads
    shape = (cfg.n_layers, batch_size, max_len, kvh, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
    }


def insert_slot_kv(pool, slot_cache, slot):
    """Write one request's freshly-prefilled cache into batch slot ``slot`` of
    a slot-pool cache (continuous batching: a queued request joins the running
    decode batch without draining it).

    pool: {"k","v"} [L, n_slots, max_len, kvh, dh]; slot_cache: the same with
    a batch dim of 1; ``slot`` is a TRACED scalar — one compiled insert
    program covers every slot. The whole [max_len] row is overwritten, so
    nothing from the slot's previous occupant survives."""
    return {
        "k": jax.lax.dynamic_update_slice(
            pool["k"], slot_cache["k"].astype(pool["k"].dtype),
            (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            pool["v"], slot_cache["v"].astype(pool["v"].dtype),
            (0, slot, 0, 0, 0)),
    }


def reset_slot_kv(pool, slot):
    """Zero batch slot ``slot`` of a slot-pool cache (optional hygiene when a
    request frees its slot; the causal mask already keeps stale rows out of
    every later request's attention window, and ``insert_slot_kv`` overwrites
    the full row — this is for debugging / belt-and-braces serving modes)."""
    z = jnp.zeros(pool["k"].shape[:1] + (1,) + pool["k"].shape[2:],
                  pool["k"].dtype)
    return {
        "k": jax.lax.dynamic_update_slice(pool["k"], z, (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            pool["v"], z.astype(pool["v"].dtype), (0, slot, 0, 0, 0)),
    }


# ---------------------------------------------------------------------------
# paged (block) KV cache: fixed pool of token blocks + per-slot block table
# ---------------------------------------------------------------------------

def init_paged_cache(cfg, n_blocks, block_size, dtype=None, kv_dtype=None):
    """Allocate the paged KV pool: ``n_blocks`` physical blocks of
    ``block_size`` tokens each, stacked over layers (a physical block id
    addresses the same block row in EVERY layer, so host allocation is one
    decision per token block, not per layer).

    ``kv_dtype="int8"`` stores blocks as int8 payloads with per-(token, head)
    fp32 scales (``comm/collectives.py`` blockwise kernels, ZeRO++ idiom) —
    k/v: [L, n_blocks, block_size, kvh, dh] int8, k_scale/v_scale:
    [L, n_blocks, block_size, kvh, 1] f32."""
    dtype = dtype or cfg.compute_dtype
    kvh = cfg.kv_heads
    shape = (cfg.n_layers, n_blocks, block_size, kvh, cfg.head_dim)
    if kv_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _dequant_layer(q, scale, dtype):
    """int8 payload + per-(token, head) scale -> ``dtype``. ``scale`` keeps
    its trailing 1-axis: it is the [..., n // block] axis
    ``dequantize_blockwise`` blocks the last payload axis by (block == dh,
    one scale per head vector)."""
    from ..comm.collectives import dequantize_blockwise

    return dequantize_blockwise(q, scale, dtype=dtype)


def _paged_view(kc, sc, table, view_dtype):
    """Gather a slot-major dense view of the pool through the block table.

    kc: [n_blocks, bs, kvh, dh] (one layer); table: [S, NB] physical block
    ids; returns [S, NB * bs, kvh, dh] — row ``s`` holds slot s's KV window
    in position order (block j covers positions [j*bs, (j+1)*bs)), exactly
    the dense cache layout, so the attention math downstream is the SAME
    program as the dense per-row path."""
    nb, bs, kvh, dh = kc.shape
    s_dim, per_slot = table.shape
    g = kc[table]                                    # [S, NB, bs, kvh, dh]
    if sc is not None:
        g = _dequant_layer(g, sc[table], view_dtype)
    return g.reshape(s_dim, per_slot * bs, kvh, dh)


def _paged_writeback(kc, sc, view, table, pos, block_size, valid=None):
    """Scatter the row each slot just wrote (at its cursor) from the dense
    view back into the pool at (table[s, pos // bs], pos % bs). Freed slots
    carry an all-garbage-block table row, so their dead writes land in the
    reserved garbage block instead of corrupting a reallocated block.

    ``valid`` ([S] bool, optional): rows whose write must instead be
    redirected to the reserved garbage block 0 (speculative verify's padded
    draft rows — they can lie past the slot's bound blocks or the KV window,
    and a clamped block index would silently corrupt a REAL block)."""
    rows = jax.vmap(
        lambda c, p: jax.lax.dynamic_slice(
            c, (p, 0, 0), (1,) + c.shape[1:]))(view, pos)[:, 0]  # [S, kvh, dh]
    return _paged_writeback_rows(kc, sc, rows, table, pos, block_size,
                                 valid=valid)


def _paged_writeback_rows(kc, sc, rows, table, pos, block_size, valid=None):
    """``_paged_writeback`` for callers that already hold the fresh
    [S, kvh, dh] rows (the fused kernel path never materializes a view to
    slice them from)."""
    j = jnp.clip(pos // block_size, 0, table.shape[1] - 1)
    bi = jnp.take_along_axis(table, j[:, None], axis=1)[:, 0]
    if valid is not None:
        bi = jnp.where(valid, bi, 0)  # block 0 = the reserved garbage block
    off = pos % block_size
    if sc is not None:
        from ..comm.collectives import quantize_blockwise

        q, scale = quantize_blockwise(rows, block=rows.shape[-1])
        return kc.at[bi, off].set(q), sc.at[bi, off].set(scale)
    return kc.at[bi, off].set(rows.astype(kc.dtype)), None


def _project_qkv(cfg, p_attn, h, rope=None):
    """The q/k/v projection + rotary application shared by every cached
    attention path — ONE implementation, so the fused paged backend can
    never diverge from the gather/dense path's projection semantics (the
    bitwise-parity contract starts here)."""
    b, q_len, _ = h.shape
    q = L.linear_apply(p_attn["q"], h).reshape(b, q_len, cfg.n_heads,
                                               cfg.head_dim)
    k = L.linear_apply(p_attn["k"], h).reshape(b, q_len, cfg.kv_heads,
                                               cfg.head_dim)
    v = L.linear_apply(p_attn["v"], h).reshape(b, q_len, cfg.kv_heads,
                                               cfg.head_dim)
    if rope is not None:
        cos, sin = rope
        q = L.apply_rotary(q, cos, sin, cfg.rotary_dim,
                           cfg.rotary_interleaved)
        k = L.apply_rotary(k, cos, sin, cfg.rotary_dim,
                           cfg.rotary_interleaved)
    return q, k, v


def _attn_paged_fused(cfg, p_attn, h, kc, vc, ks, vs, table, pos, rope=None):
    """The fused-backend twin of ``_attn_with_cache`` for paged decode
    (q_len == 1): project q/k/v for the current token, then attend straight
    against the POOL through the split-KV flash-decode kernel — the block
    table walks inside the kernel's index map, so no dense per-slot view is
    ever materialized. Returns ``(out [S, 1, d], k_row, v_row)`` with the
    fresh [S, kvh, dh] rows for the caller's pool writeback (the kernel
    already folded them into the softmax in compute dtype, exactly the
    value the gather path attends at the cursor)."""
    from ..ops.pallas.paged_attention import paged_flash_decode

    b, q_len, _ = h.shape
    q, k, v = _project_qkv(cfg, p_attn, h, rope=rope)
    slopes = L.alibi_slopes(cfg.n_heads) \
        if cfg.position_embedding == "alibi" else None
    out = paged_flash_decode(q[:, 0], k[:, 0], v[:, 0], kc, vc, table, pos,
                             k_scale=ks, v_scale=vs, scale=cfg.attn_scale,
                             alibi_slopes=slopes)
    out = L.linear_apply(p_attn["o"], out.reshape(b, q_len, -1))
    return out, k[:, 0], v[:, 0]


def forward_with_paged_cache(model, params, input_ids, pool, table, pos,
                             block_size, draft_len=None,
                             attention_backend="gather"):
    """One decode step ([S, 1] tokens) reading/writing KV through a TRACED
    block table — the paged twin of ``forward_with_cache``'s per-row decode.

    Per layer: gather the slot-major dense view through ``table``
    (dequantizing int8 blocks), run the UNCHANGED dense per-row attention on
    it (``_block_cached``), then scatter each slot's newly-written row back
    into the pool. Because the gathered view is bit-identical to the dense
    cache at every unmasked position and the math in between is the same
    program, greedy paged decode is bitwise-equal to the dense slot pool
    (tier-1 pins it). Returns (logits [S, 1, vocab], new pool).

    ``draft_len`` [S] switches the program into speculative VERIFY mode
    (see ``verify_with_paged_cache``): ``input_ids`` becomes [S, k+1]
    (the slot's last token + k draft candidates at per-slot cursors), all
    k+1 rows are written and all k+1 logit rows returned. Row i's write
    could ever become live only while ``i <= draft_len`` and the position
    is inside the KV window — padded rows compute garbage that the causal
    mask hides in-view and whose pool writeback redirects to the garbage
    block, and the in-view writes run in reverse row order so a
    window-clamped padded write can never shadow a real row.

    ``attention_backend="fused"`` replaces the per-layer gather + dense
    attention + scatter with the split-KV flash-decode kernel
    (``ops/pallas/paged_attention.py``): the block-table walk happens
    inside the kernel's index map and the dense per-slot view is never
    materialized. Decode-only (q_len == 1, no verify) — callers gate on
    ``fused_decode_supported`` and fall back to the gather path."""
    cfg = model.config
    b, q_len = input_ids.shape
    int8 = "k_scale" in pool
    view_dtype = cfg.compute_dtype
    fused = attention_backend == "fused"
    if fused and (draft_len is not None or q_len != 1):
        raise ValueError(
            "attention_backend='fused' is decode-only (one query row per "
            "slot); speculative verify runs the gather path")
    if fused and cfg.local_attention_window > 0:
        raise ValueError(
            "attention_backend='fused' does not implement banded local-"
            "attention masks (fused_decode_supported gates this)")
    positions = pos[:, None] + jnp.arange(q_len)[None, :]
    kv_len = table.shape[1] * block_size
    if draft_len is not None:
        valid = (jnp.arange(q_len)[None, :] <= draft_len[:, None]) \
            & (positions < kv_len)                    # [S, q]
        row_writes = "reverse"
    else:
        valid = None
        row_writes = "block"

    x = L.embedding_apply(params["wte"], input_ids, cfg.compute_dtype)
    if cfg.position_embedding == "learned":
        # jnp.take clamps out-of-range (padded-row) positions; those rows'
        # embeddings are garbage by design and masked/redirected above
        x = x + jnp.take(params["wpe"]["weight"].astype(cfg.compute_dtype),
                         positions, axis=0)
    rope = None
    if cfg.position_embedding == "rope":
        rope = L.rotary_embedding(positions, cfg.rotary_dim or cfg.head_dim,
                                  cfg.rope_base)

    def block_step(h, p_i, kc, vc, ks, vs, loc):
        if fused:
            def attn_impl(p_attn, hh):
                return _attn_paged_fused(cfg, p_attn, hh, kc, vc, ks, vs,
                                         table, pos, rope=rope)

            h, k_row, v_row = _block_cached(cfg, p_i, h, None, None, pos,
                                            kv_len, rope=rope,
                                            attn_impl=attn_impl)
            kc, ks = _paged_writeback_rows(kc, ks, k_row, table, pos,
                                           block_size)
            vc, vs = _paged_writeback_rows(vc, vs, v_row, table, pos,
                                           block_size)
            return h, kc, vc, ks, vs
        kview = _paged_view(kc, ks, table, view_dtype)
        vview = _paged_view(vc, vs, table, view_dtype)
        h, kview, vview = _block_cached(cfg, p_i, h, kview, vview, pos,
                                        kv_len, rope=rope, is_local=loc,
                                        row_writes=row_writes)
        for i in range(q_len):
            p_row = pos if i == 0 else pos + i
            v_row = None if valid is None else valid[:, i]
            kc, ks = _paged_writeback(kc, ks, kview, table, p_row,
                                      block_size, valid=v_row)
            vc, vs = _paged_writeback(vc, vs, vview, table, p_row,
                                      block_size, valid=v_row)
        return h, kc, vc, ks, vs

    scales = (pool["k_scale"], pool["v_scale"]) if int8 else None
    if cfg.local_attention_window > 0:
        from .transformer import local_attention_flags

        is_local_arr = jnp.asarray(local_attention_flags(cfg))
    else:
        is_local_arr = None

    def scan_fn(carry, layer):
        h = carry
        if int8:
            if is_local_arr is not None:
                p_i, kc, vc, ks, vs, loc = layer
            else:
                (p_i, kc, vc, ks, vs), loc = layer, None
        else:
            ks = vs = None
            if is_local_arr is not None:
                p_i, kc, vc, loc = layer
            else:
                (p_i, kc, vc), loc = layer, None
        h, kc, vc, ks, vs = block_step(h, p_i, kc, vc, ks, vs, loc)
        out = (kc, vc, ks, vs) if int8 else (kc, vc)
        return h, out

    xs = [params["blocks"], pool["k"], pool["v"]]
    if int8:
        xs += [scales[0], scales[1]]
    if is_local_arr is not None:
        xs += [is_local_arr]
    h, new = jax.lax.scan(scan_fn, x, tuple(xs))
    h = _norm_apply(cfg, params["ln_f"], h)
    if cfg.tie_embeddings:
        logits = L.embedding_attend(params["wte"], h)
    else:
        logits = L.linear_apply(params["lm_head"], h)
    new_pool = {"k": new[0], "v": new[1]}
    if int8:
        new_pool["k_scale"], new_pool["v_scale"] = new[2], new[3]
    return logits, new_pool


def verify_with_paged_cache(model, params, input_ids, pool, table, pos,
                            block_size, draft_len):
    """One speculative-decoding VERIFY step against the paged cache: feed
    ``input_ids`` [S, k+1] (each slot's last sampled token + its k draft
    candidates) at per-slot cursors ``pos``, write the candidate KV rows,
    and return ALL k+1 logit rows — the single target forward classic
    speculative decoding needs (arXiv:2211.17192). Row i's logits give the
    target's next-token distribution after consuming row i, so greedy
    acceptance is: take drafts while ``draft[i] == argmax(logits[:, i])``.

    This IS ``forward_with_paged_cache`` with ``draft_len`` set — the same
    gather/attention/writeback scaffold as the decode program, so the
    logits at every accepted position are bitwise what sequential decode
    would have produced there (the multi-position == sequential property
    the suffix-prefill/chunked paths already pin). Rejected candidates'
    rows stay in the pool PAST the rolled-back cursor — causally masked,
    overwritten before they could become visible; the serving engine
    additionally releases/scrubs fully-stale blocks at block granularity.

    Returns (logits [S, k+1, vocab], new pool)."""
    return forward_with_paged_cache(model, params, input_ids, pool, table,
                                    pos, block_size, draft_len=draft_len)


def insert_block_kv(pool, dense_cache, block_id, src_start, block_size):
    """Copy ONE token block from a freshly-prefilled dense cache into
    physical block ``block_id`` of the pool (quantizing when the pool is
    int8). ``block_id``/``src_start`` are TRACED scalars — one compiled
    program covers every (block, offset) pair. The whole block is
    overwritten, so nothing from its previous occupant survives (the paged
    analogue of ``insert_slot_kv``'s whole-row guarantee)."""
    out = dict(pool)
    for name in ("k", "v"):
        rows = jax.lax.dynamic_slice_in_dim(
            dense_cache[name], src_start, block_size, axis=2)  # [L,1,bs,kvh,dh]
        rows = jnp.swapaxes(rows, 1, 2)[:, :, 0]               # [L,bs,kvh,dh]
        if name + "_scale" in pool:
            from ..comm.collectives import quantize_blockwise

            q, scale = quantize_blockwise(rows, block=rows.shape[-1])
            out[name] = jax.lax.dynamic_update_slice(
                pool[name], q[:, None], (0, block_id, 0, 0, 0))
            out[name + "_scale"] = jax.lax.dynamic_update_slice(
                pool[name + "_scale"], scale[:, None],
                (0, block_id, 0, 0, 0))
        else:
            out[name] = jax.lax.dynamic_update_slice(
                pool[name], rows[:, None].astype(pool[name].dtype),
                (0, block_id, 0, 0, 0))
    return out


def reset_block_kv(pool, block_id):
    """Zero physical block ``block_id`` (block-granularity hygiene scrub —
    ``scrub_freed_slots`` generalized from the dense pool's whole-row
    scrub; int8 scales zero too, so a dequantized read is exactly 0)."""
    out = {}
    for name, a in pool.items():
        z = jnp.zeros(a.shape[:1] + (1,) + a.shape[2:], a.dtype)
        out[name] = jax.lax.dynamic_update_slice(a, z, (0, block_id, 0, 0, 0))
    return out


def gather_slot_cache(cfg, pool, table_row, dtype):
    """Materialize one slot's dense [L, 1, NB*bs, kvh, dh] cache view from
    its block-table row (dequantizing int8 blocks) — seeds the suffix
    prefill on a shared-prefix hit: positions below the shared length hold
    the canonical prefix KV, everything above is garbage the suffix prefill
    overwrites or the causal mask hides."""
    g = pool["k"][:, table_row]                    # [L, NB, bs, kvh, dh]
    gv = pool["v"][:, table_row]
    if "k_scale" in pool:
        g = _dequant_layer(g, pool["k_scale"][:, table_row], dtype)
        gv = _dequant_layer(gv, pool["v_scale"][:, table_row], dtype)
    L_, nb, bs, kvh, dh = g.shape
    return {"k": g.reshape(L_, 1, nb * bs, kvh, dh).astype(dtype),
            "v": gv.reshape(L_, 1, nb * bs, kvh, dh).astype(dtype)}


def extract_slot_blocks(pool, table_row):
    """RAW gather of one slot's physical blocks for live migration: every
    pool leaf at its stored dtype — k/v payloads (int8 or dense) AND the
    int8 scales when present — stacked [L, NB, bs, kvh, dh|1] in table-row
    order. No dequantization: a dequant -> requant round trip reproduces
    the int8 payload but can perturb the recomputed scale in its last ulp,
    which would break the migrated-stream-is-bitwise contract. Padded
    table entries (GARBAGE_BLOCK) gather the garbage block; the injector
    ignores them via its own id padding."""
    return {name: a[:, table_row] for name, a in pool.items()}


def inject_block_kv(pool, raw_blocks, block_id, src_block):
    """Copy ONE raw migrated block (``extract_slot_blocks`` payload, pool
    dtype end to end — scales included) into physical block ``block_id``.
    ``block_id``/``src_block`` are TRACED scalars, so one compiled program
    covers every (target, source) pair; padded targets point at the
    reserved garbage block, same convention as the prefill insert loop.
    The whole block is overwritten — nothing from its previous occupant
    survives, and because no quantize/dequantize runs, the target pool
    bytes are identical to the source pool bytes (the bitwise-migration
    contract's device half)."""
    out = dict(pool)
    for name, a in pool.items():
        rows = jax.lax.dynamic_slice_in_dim(
            raw_blocks[name], src_block, 1, axis=1)        # [L,1,bs,kvh,*]
        out[name] = jax.lax.dynamic_update_slice(
            a, rows.astype(a.dtype), (0, block_id, 0, 0, 0))
    return out


def _attn_with_cache(cfg, p_attn, h, k_cache, v_cache, pos, kv_len, rope=None,
                     is_local=None, prefill=False, row_writes="block"):
    """Attention for q block [b, q, d] against cache[:, :kv_len] after writing the
    new k/v at ``pos``. Returns (out [b, q, d], new k_cache, new v_cache).

    k_cache/v_cache: [b, max_len, kvh, dh]; pos: scalar write offset, OR a
    per-row [b] vector (continuous-batching slot pools, where each co-batched
    request sits at its own cursor); kv_len: static upper bound on valid cache
    length (mask handles the rest).
    ``prefill``: static caller promise that pos == 0 and the q block IS the
    whole visible window — enables the flash fast path below (scalar pos only).
    ``row_writes`` (per-row pos only): "block" writes the whole q block with
    one update per row; "reverse" writes one position at a time, LAST
    position first — required when pos + q may legitimately overrun the
    window (speculative verify's padded draft rows): an overrunning write
    clamps onto the final row, and the reverse order guarantees the valid
    write at any clamp target lands last, so clamped garbage can never
    shadow a real row (the PR 7 overrun class, closed by ordering instead
    of a bucket cap because here the overrun is by design).
    """
    b, q_len, d = h.shape
    per_row = jnp.ndim(pos) == 1
    q, k, v = _project_qkv(cfg, p_attn, h, rope=rope)

    if per_row:
        # each row writes its q block at its OWN cursor (slot-pool decode);
        # vmapped dynamic_update_slice lowers to a per-row scatter
        row_update = jax.vmap(
            lambda c, blk, p: jax.lax.dynamic_update_slice(c, blk, (p, 0, 0)))
        if row_writes == "reverse":
            for i in reversed(range(q_len)):
                k_cache = row_update(k_cache,
                                     k[:, i:i + 1].astype(k_cache.dtype),
                                     pos + i)
                v_cache = row_update(v_cache,
                                     v[:, i:i + 1].astype(v_cache.dtype),
                                     pos + i)
        else:
            k_cache = row_update(k_cache, k.astype(k_cache.dtype), pos)
            v_cache = row_update(v_cache, v.astype(v_cache.dtype), pos)
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                               (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                               (0, pos, 0, 0))

    # Prefill is plain causal attention over the just-written prompt rows:
    # cache slot j >= q_len is in the causal future of every query, so the
    # [q, max_len] window the dense path masks away never needs to exist.
    # Route it through the flash kernel so TTFT doesn't pay the O(s^2)
    # logits materialization — on the fresh k/v (cast through the cache
    # dtype to keep the dense path's numerics), repeated BEFORE any cache
    # read so no [b, max_len, heads, dh] tensor materializes. prefill_flash:
    # True/False force, None = TPU backend only (the CPU fallback is the
    # chunked-XLA flash, correct everywhere).
    flash_wanted = cfg.prefill_flash
    if flash_wanted is None:
        flash_wanted = jax.default_backend() == "tpu"
    if (flash_wanted and prefill and not per_row and q_len > 1
            and is_local is None and cfg.position_embedding != "alibi"):
        from ..ops.flash_attention import flash_attention

        n_rep = cfg.n_heads // cfg.kv_heads
        out = flash_attention(q,
                              L._repeat_kv(k.astype(k_cache.dtype), n_rep),
                              L._repeat_kv(v.astype(v_cache.dtype), n_rep),
                              causal=True, scale=cfg.attn_scale,
                              block_q=cfg.flash_block_q,
                              block_kv=cfg.flash_block_kv)
        out = L.linear_apply(p_attn["o"], out.reshape(b, q_len, -1))
        return out, k_cache, v_cache

    k_full = L._repeat_kv(k_cache[:, :kv_len], cfg.n_heads // cfg.kv_heads)
    v_full = L._repeat_kv(v_cache[:, :kv_len], cfg.n_heads // cfg.kv_heads)

    # causal vs the cache: query i (global pos+i) sees cache slots <= pos+i
    if per_row:
        kv_idx = jnp.arange(kv_len)[None, None, :]                 # [1, 1, kv]
        q_idx = pos[:, None, None] + jnp.arange(q_len)[None, :, None]  # [b, q, 1]
    else:
        kv_idx = jnp.arange(kv_len)[None, :]
        q_idx = pos + jnp.arange(q_len)[:, None]
    allowed = kv_idx <= q_idx
    if cfg.local_attention_window > 0 and is_local is not None:
        # banded local layers (GPT-Neo): is_local is a traced per-layer bool
        band = q_idx - kv_idx < cfg.local_attention_window
        allowed = allowed & (band | jnp.logical_not(is_local))
    # [b, 1, q, kv] (per-row cursors) or [1, 1, q, kv] (shared cursor)
    mask = allowed[:, None, :, :] if per_row else allowed[None, None, :, :]

    alibi = None
    if cfg.position_embedding == "alibi":
        if per_row:
            # slopes * (kv - q) per row — the same int-difference-then-
            # fp32-multiply as _alibi_slice, so per-row values are bitwise
            # equal to the scalar-cursor path at the same positions
            dist = (kv_idx - q_idx).astype(jnp.float32)  # [b, q, kv]
            alibi = (L.alibi_slopes(cfg.n_heads)[None, :, None, None]
                     * dist[:, None, :, :])
        else:
            alibi = _alibi_slice(cfg, q_len, kv_len, pos)

    out = L.dot_product_attention(
        q, k_full, v_full, mask=mask, scale=cfg.attn_scale, alibi_bias=alibi,
        # bf16 logits cut prefill TTFT's [b,h,s,s] HBM traffic too; decode
        # steps ([b,h,1,kv]) are unaffected either way
        logits_dtype=cfg.attn_logits_jnp_dtype)
    # -1, not d: head-pruned models have attention width n_heads*head_dim < d
    out = L.linear_apply(p_attn["o"], out.reshape(b, q_len, -1))
    return out, k_cache, v_cache


def _alibi_slice(cfg, q_len, kv_len, pos):
    """ALiBi bias for queries at global positions [pos, pos+q) vs keys [0, kv)."""
    full = L.alibi_bias(cfg.n_heads, kv_len, kv_len)  # [h|1xh, kv, kv] layout
    # L.alibi_bias returns [1, heads, q, kv]; slice the query rows
    return jax.lax.dynamic_slice_in_dim(full, pos, q_len, axis=2)


def _mlp(cfg, p, h):
    if cfg.n_experts > 0:
        from ..moe import moe_mlp_apply

        out, _ = moe_mlp_apply(cfg, p["mlp"], h, deterministic=True)
        return out
    act = L.ACTIVATIONS[cfg.activation] if cfg.activation != "swiglu" else None
    mp = jax.tree_util.tree_map(
        lambda a: a.astype(h.dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, p["mlp"])
    if cfg.activation == "swiglu":
        gate = L.linear_apply(mp["gate"], h)
        up = L.linear_apply(mp["up"], h)
        return L.linear_apply(mp["down"], jax.nn.silu(gate) * up)
    return L.linear_apply(mp["proj"], act(L.linear_apply(mp["fc"], h)))


def _block_cached(cfg, p, x, k_cache, v_cache, pos, kv_len, rope=None,
                  is_local=None, prefill=False, row_writes="block",
                  attn_impl=None):
    """One block with cache. x: [b, q, d] compute dtype.

    ``attn_impl(p_attn_cast, h) -> (out, aux1, aux2)`` overrides the dense
    ``_attn_with_cache`` (the fused paged backend routes the flash-decode
    kernel through here so the norm/residual/MLP structure — and therefore
    parity with the gather path — is shared by construction); the two aux
    values replace the (k_cache, v_cache) return slots."""
    cast = lambda a: a.astype(cfg.compute_dtype) \
        if jnp.issubdtype(a.dtype, jnp.floating) else a
    p_cast = {
        "ln_1": p["ln_1"],
        "ln_2": p["ln_2"],
        "attn": jax.tree_util.tree_map(cast, p["attn"]),
        "mlp": p["mlp"],
    }

    def attn(h):
        if attn_impl is not None:
            return attn_impl(p_cast["attn"], h)
        return _attn_with_cache(cfg, p_cast["attn"], h, k_cache, v_cache, pos,
                                kv_len, rope=rope, is_local=is_local,
                                prefill=prefill, row_writes=row_writes)

    if cfg.parallel_attn_mlp:
        h = _norm_apply(cfg, p_cast["ln_1"], x)
        h_mlp = _norm_apply(cfg, p_cast["ln_2"], x) \
            if cfg.parallel_norm_split else h
        a, kc, vc = attn(h)
        return x + a + _mlp(cfg, p_cast, h_mlp), kc, vc
    if cfg.prenorm:
        a, kc, vc = attn(_norm_apply(cfg, p_cast["ln_1"], x))
        x = x + a
        x = x + _mlp(cfg, p_cast, _norm_apply(cfg, p_cast["ln_2"], x))
        return x, kc, vc
    a, kc, vc = attn(x)
    x = _norm_apply(cfg, p_cast["ln_1"], x + a)
    x = _norm_apply(cfg, p_cast["ln_2"], x + _mlp(cfg, p_cast, x))
    return x, kc, vc


def forward_with_cache(model, params, input_ids, cache, pos, kv_len,
                       prefill=False, row_writes="block"):
    """Run the model on ``input_ids`` [b, q] writing k/v into ``cache`` at ``pos``.

    Used for both prefill (q = prompt length, pos = 0) and decode (q = 1,
    pos = cursor). ``pos`` may be a scalar (whole batch at one cursor) or a
    [b] vector (slot-pool continuous batching: every row at its own cursor).
    Returns (logits [b, q, vocab], new_cache).
    ``prefill=True`` is the caller's static promise that pos == 0 and the
    whole visible window is this q block — it unlocks the flash fast path
    (callers with pos > 0 must leave it False).
    ``row_writes="reverse"`` (per-row pos only) makes multi-row writes safe
    against by-design window overruns — see ``_attn_with_cache``.
    """
    cfg = model.config
    b, q_len = input_ids.shape
    if jnp.ndim(pos) == 1:
        positions = pos[:, None] + jnp.arange(q_len)[None, :]  # [b, q]
    else:
        positions = pos + jnp.arange(q_len)[None, :]
        positions = jnp.broadcast_to(positions, (b, q_len))

    x = L.embedding_apply(params["wte"], input_ids, cfg.compute_dtype)
    if cfg.position_embedding == "learned":
        x = x + jnp.take(params["wpe"]["weight"].astype(cfg.compute_dtype),
                         positions, axis=0)
    rope = None
    if cfg.position_embedding == "rope":
        rope = L.rotary_embedding(positions, cfg.rotary_dim or cfg.head_dim,
                                  cfg.rope_base)

    if cfg.local_attention_window > 0:
        from .transformer import local_attention_flags

        is_local_arr = jnp.asarray(local_attention_flags(cfg))

        def scan_fn(carry, layer):
            h = carry
            p_i, kc, vc, loc = layer
            h, kc, vc = _block_cached(cfg, p_i, h, kc, vc, pos, kv_len,
                                      rope=rope, is_local=loc,
                                      prefill=prefill, row_writes=row_writes)
            return h, (kc, vc)

        h, (k_new, v_new) = jax.lax.scan(
            scan_fn, x, (params["blocks"], cache["k"], cache["v"], is_local_arr)
        )
    else:
        def scan_fn(carry, layer):
            h = carry
            p_i, kc, vc = layer
            h, kc, vc = _block_cached(cfg, p_i, h, kc, vc, pos, kv_len,
                                      rope=rope, prefill=prefill,
                                      row_writes=row_writes)
            return h, (kc, vc)

        h, (k_new, v_new) = jax.lax.scan(
            scan_fn, x, (params["blocks"], cache["k"], cache["v"])
        )
    h = _norm_apply(cfg, params["ln_f"], h)
    if cfg.tie_embeddings:
        logits = L.embedding_attend(params["wte"], h)
    else:
        logits = L.linear_apply(params["lm_head"], h)
    return logits, {"k": k_new, "v": v_new}


def sample_token(logits, rng, *, temperature=1.0, top_k=0, top_p=1.0,
                 greedy=False):
    """logits: [b, vocab] -> [b] int32.

    ``greedy``, ``top_k`` and ``top_p`` are static (shape the program);
    ``temperature`` may be a TRACED scalar so serving/rollout loops can change
    it without recompiling (the reference recompiles nothing — CUDA kernels
    take it as a runtime arg; so do we).

    PER-REQUEST mode: pass ``rng`` as a [b, 2] stack of PRNG keys and
    temperature/top_k/top_p as [b] arrays — every co-batched row then samples
    from its OWN rng stream with its own knobs (continuous-batching slot
    pools), all traced so one compiled program covers every mix. Rows with
    temperature <= 0 are greedy."""
    if jnp.ndim(rng) == 2:
        return sample_token_per_request(logits, rng, temperature=temperature,
                                        top_k=top_k, top_p=top_p)
    logits = logits.astype(jnp.float32)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if isinstance(temperature, (int, float)) and temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if isinstance(top_p, (int, float)) and 0.0 < top_p < 1.0:
        logits = _apply_top_p(logits, jnp.full((logits.shape[0],), top_p,
                                               jnp.float32))
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _apply_top_p(logits, top_p, sorted_desc=None):
    """Nucleus filter: per row, keep the smallest prefix of descending-prob
    tokens whose cumulative probability reaches ``top_p``; mask the rest.
    ``top_p`` [b] traced; rows with top_p >= 1 pass through unchanged.
    ``sorted_desc``: optionally pass ``sort(logits)`` descending to reuse a
    sort the caller already paid for (the serving decode hot path)."""
    if sorted_desc is None:
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    # exclusive prefix sum: token j is kept while the mass BEFORE it is < p
    # (so the token that crosses p is included — standard nucleus semantics)
    prefix = jnp.cumsum(probs, axis=-1) - probs
    keep = prefix < top_p[:, None]
    # the top token is ALWAYS kept: top_p <= 0 would otherwise keep nothing,
    # mask everything to -1e30, and sample uniformly over the whole vocab
    keep = keep.at[:, 0].set(True)
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    filtered = jnp.where(logits < cutoff, -1e30, logits)
    return jnp.where(top_p[:, None] >= 1.0, logits, filtered)


def sample_token_per_request(logits, rngs, *, temperature, top_k, top_p):
    """Per-request sampling for a slot pool: logits [b, vocab], rngs [b, 2]
    (one PRNG key per row — co-batched requests NEVER share an rng stream),
    temperature/top_k/top_p [b] traced arrays. Rows with temperature <= 0
    take the exact argmax (same tie-breaking as the scalar greedy path).
    Returns [b] int32. Everything is traced: requests with any knob mix
    join/leave the batch without recompiling."""
    logits = logits.astype(jnp.float32)
    b, vocab = logits.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # per-row top-k: threshold at the k-th largest (k <= 0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(top_k, 0, vocab)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(k - 1, 0, vocab - 1)[:, None], axis=-1)
    below_kth = lambda a: (k[:, None] > 0) & (a < kth)
    scaled = jnp.where(below_kth(scaled), -1e30, scaled)
    # masking the same tail in the already-sorted array keeps it sorted —
    # one O(b * V log V) sort per decode step, not two
    sorted_masked = jnp.where(below_kth(sorted_desc), -1e30, sorted_desc)
    scaled = _apply_top_p(scaled, top_p, sorted_desc=sorted_masked)

    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(rngs, scaled)
    return jnp.where(temperature <= 0.0, greedy_tok,
                     sampled.astype(jnp.int32))


def prefill_and_first_token(model, params, ids, rng, temperature, *, max_len,
                            greedy, top_k, dtype, true_len=None):
    """Prefill the KV cache with the prompt and sample the first new token.
    Shared by the serving engine and the hybrid (RLHF) engine — one
    implementation of the rollout math, two jit wrappers.

    ``true_len`` (traced scalar) supports right-padded bucketed prompts: the
    first token is sampled at column ``true_len - 1`` instead of the last
    column. Pad slots beyond ``true_len`` hold garbage k/v but always sit in
    the causally-masked future of every real query, and the decode loop
    overwrites each one exactly when its position enters the window — so no
    mask tensor is needed (the serving engine recompiles per prompt LENGTH
    BUCKET, not per length; cf. the reference re-using one CUDA workspace
    across lengths)."""
    b, prompt_len = ids.shape
    cache = init_cache(model.config, b, max_len, dtype)
    logits, cache = forward_with_cache(model, params, ids, cache, 0, max_len,
                                       prefill=True)
    if true_len is None:
        last = logits[:, prompt_len - 1]
    else:
        last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)[:, 0]
    tok = sample_token(last, rng, temperature=temperature,
                       top_k=top_k, greedy=greedy)
    return tok, cache


def decode_tokens(model, params, cache, tok, rng, temperature, *, prompt_len,
                  max_len, steps, greedy, top_k):
    """Scan ``steps`` single-token decode iterations.

    Returns ``(toks [steps, b], cache)``. The final cache is returned (even
    though callers usually drop it) so a caller that donates the input cache
    gives XLA an output to alias — otherwise the donation is unusable and the
    compiled program copies the cache at loop entry."""

    def step(carry, i):
        cache, tok, rng = carry
        rng, r = jax.random.split(rng)
        logits, cache = forward_with_cache(
            model, params, tok[:, None], cache, prompt_len + i, max_len)
        nxt = sample_token(logits[:, 0], r, temperature=temperature,
                           top_k=top_k, greedy=greedy)
        return (cache, nxt, rng), nxt

    (cache, _, _), toks = jax.lax.scan(step, (cache, tok, rng),
                                       jnp.arange(steps))
    return toks, cache


def decode_tokens_until(model, params, cache, tok, rng, temperature, *,
                        prompt_len, max_len, steps, greedy, top_k,
                        eos_token_id):
    """Early-stopping decode: a ``while_loop`` that exits as soon as EVERY row
    has emitted ``eos_token_id`` (the reference's generate-stops-at-eos
    behavior, but inside the compiled program — short answers don't pay for
    ``max_new_tokens`` iterations). Rows that finished keep emitting eos.
    Returns ``(out [steps, b], cache)`` (positions past a row's eos filled
    with eos; the cache is returned for donation aliasing, see
    ``decode_tokens``)."""
    b = tok.shape[0]
    out0 = jnp.full((steps, b), eos_token_id, jnp.int32)
    done0 = tok == eos_token_id

    def cond(carry):
        i, done, *_ = carry
        return jnp.logical_and(i < steps, jnp.logical_not(jnp.all(done)))

    def body(carry):
        i, done, cache, tok, rng, out = carry
        rng, r = jax.random.split(rng)
        logits, cache = forward_with_cache(
            model, params, tok[:, None], cache, prompt_len + i, max_len)
        nxt = sample_token(logits[:, 0], r, temperature=temperature,
                           top_k=top_k, greedy=greedy)
        nxt = jnp.where(done, jnp.asarray(eos_token_id, jnp.int32), nxt)
        out = out.at[i].set(nxt)
        done = jnp.logical_or(done, nxt == eos_token_id)
        return (i + 1, done, cache, nxt, rng, out)

    (_, _, cache, _, _, out) = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), done0, cache, tok, rng, out0))
    return out, cache
