from .layers import Param, split_params_axes
from .transformer import (CausalLM, MaskedLM, TextEncoder,
                          TransformerConfig, cross_entropy_loss)
from .registry import (get_model, MODEL_CONFIGS, gpt2_config, opt_config,
                       bloom_config, llama_config, bert_config,
                       mistral_config, gptj_config, neox_config,
                       falcon_config, gpt_neo_config)
from .simple import SimpleModel, random_batch
from .spatial import (DSUNet, DSVAE, SpatialConfig, SpatialUNet,
                      SpatialVAEDecoder)
from .diffusers_import import (load_diffusers_unet, load_diffusers_vae_decoder,
                               export_diffusers_unet,
                               export_diffusers_vae_decoder)

__all__ = [
    "MaskedLM",
    "TextEncoder",
    "bert_config",
    "DSUNet",
    "DSVAE",
    "SpatialConfig",
    "SpatialUNet",
    "SpatialVAEDecoder",
    "load_diffusers_unet",
    "load_diffusers_vae_decoder",
    "export_diffusers_unet",
    "export_diffusers_vae_decoder",
    "Param",
    "split_params_axes",
    "CausalLM",
    "TransformerConfig",
    "cross_entropy_loss",
    "get_model",
    "MODEL_CONFIGS",
    "gpt2_config",
    "opt_config",
    "bloom_config",
    "llama_config",
    "SimpleModel",
    "random_batch",
]
