"""Spatial (diffusion) inference blocks: NHWC convs, GroupNorm, UNet/VAE.

TPU-native counterpart of the reference's spatial inference surface:

- ``csrc/spatial/`` (NHWC conv helpers + fused ``opt_bias_add.cu``): here the
  layout is NHWC end-to-end — the conv layout XLA:TPU prefers — and bias/SiLU
  fuse into the conv epilogue automatically, so the hand-written kernels
  collapse into layer functions.
- ``model_implementations/diffusers/{unet,vae}.py`` (``DSUNet``/``DSVAE``:
  cuda-graph capture over an HF diffusers module): here ``DSUNet``/``DSVAE``
  wrap OUR spatial modules with a jitted, shape-cached forward — a compiled
  XLA program is the cuda-graph equivalent (one replayable executable, zero
  Python in the hot path).
- ``ops/transformer/inference/diffusers_attention.py`` /
  ``diffusers_transformer_block.py``: the spatial self/cross-attention
  transformer block below.

Models are ``init``/``apply`` pairs over Param pytrees like the rest of the
zoo (``models/layers.py``), so ``init_inference`` TP/quant machinery applies.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp

from . import layers as L
from .layers import Param


@dataclasses.dataclass
class SpatialConfig:
    """Compact UNet/VAE geometry (diffusers UNet2DConditionModel-shaped).

    ``diffusers_geometry=True`` switches to the EXACT diffusers SD-1.x module
    graph (skip bookkeeping incl. conv_in/downsampler outputs, n_res+1-resnet
    up blocks, per-level cross-attention, proj_in/out + GEGLU transformer
    blocks) so real Stable-Diffusion checkpoints load via
    ``models/diffusers_import.py``. SD-1.5 itself is
    ``SpatialConfig(base_channels=320, channel_mults=(1, 2, 4, 4),
    n_res_blocks=2, n_heads=8, context_dim=768, groups=32,
    diffusers_geometry=True)``."""

    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 64
    channel_mults: tuple = (1, 2)
    n_res_blocks: int = 1
    n_heads: int = 4
    context_dim: int = 0        # >0 enables cross-attention (text conditioning)
    groups: int = 16
    compute_dtype: object = jnp.float32
    diffusers_geometry: bool = False
    # cross-attention per resolution level (None = diffusers SD default:
    # every level except the deepest)
    attention_levels: tuple = None

    def attn_at(self, level):
        if self.attention_levels is not None:
            return bool(self.attention_levels[level])
        return level < len(self.channel_mults) - 1


# ---------------------------------------------------------------------------------
# primitive spatial layers (NHWC)
# ---------------------------------------------------------------------------------
def conv2d_init(rng, in_ch, out_ch, kernel=3, stddev=None):
    """HWIO kernel layout. Axes: out-channels are TP-shardable ("mlp" vocab)."""
    stddev = stddev if stddev is not None else 1.0 / math.sqrt(in_ch * kernel * kernel)
    k = L.normal_init(rng, (kernel, kernel, in_ch, out_ch), stddev)
    return {
        "kernel": Param(k, (None, None, None, "mlp")),
        "bias": Param(jnp.zeros((out_ch,)), (None,)),
    }


def conv2d_apply(p, x, stride=1, compute_dtype=None):
    """x: [b, h, w, c] NHWC. Bias adds fuse into the conv epilogue (the
    reference needs ``opt_bias_add.cu`` for this; XLA does it for free)."""
    dtype = compute_dtype or x.dtype
    k = p["kernel"].astype(dtype)
    pad = (k.shape[0] - 1) // 2
    out = jax.lax.conv_general_dilated(
        x.astype(dtype), k, window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + p["bias"].astype(dtype)


def groupnorm_init(ch):
    return {"scale": Param(jnp.ones((ch,)), (None,)),
            "bias": Param(jnp.zeros((ch,)), (None,))}


def groupnorm_apply(p, x, groups, eps=1e-5, act=None):
    """GroupNorm over NHWC (+ optionally fused SiLU). fp32 statistics."""
    b, h, w, c = x.shape
    xg = x.astype(jnp.float32).reshape(b, h, w, groups, c // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(b, h, w, c) * p["scale"] + p["bias"]
    if act == "silu":
        out = jax.nn.silu(out)
    return out.astype(x.dtype)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal timestep embedding [b] -> [b, dim] (diffusion standard)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


# ---------------------------------------------------------------------------------
# blocks
# ---------------------------------------------------------------------------------
def resnet_block_init(rng, in_ch, out_ch, temb_dim):
    r = jax.random.split(rng, 4)
    p = {
        "norm1": groupnorm_init(in_ch),
        "conv1": conv2d_init(r[0], in_ch, out_ch),
        "norm2": groupnorm_init(out_ch),
        "conv2": conv2d_init(r[1], out_ch, out_ch),
    }
    if temb_dim:
        p["temb"] = L.linear_init(r[2], temb_dim, out_ch, ("embed", None))
    if in_ch != out_ch:
        p["skip"] = conv2d_init(r[3], in_ch, out_ch, kernel=1)
    return p


def resnet_block_apply(cfg, p, x, temb=None):
    h = groupnorm_apply(p["norm1"], x, cfg.groups, act="silu")
    h = conv2d_apply(p["conv1"], h)
    if temb is not None and "temb" in p:
        h = h + L.linear_apply(p["temb"], jax.nn.silu(temb))[:, None, None, :]
    h = groupnorm_apply(p["norm2"], h, cfg.groups, act="silu")
    h = conv2d_apply(p["conv2"], h)
    skip = conv2d_apply(p["skip"], x) if "skip" in p else x
    return skip + h


def spatial_transformer_init(rng, ch, n_heads, context_dim):
    """Self-attention (+ optional cross-attention) over flattened h*w tokens —
    the ``diffusers_transformer_block`` equivalent."""
    r = jax.random.split(rng, 5)
    p = {
        "norm": groupnorm_init(ch),
        "attn": L.attention_init(r[0], ch, n_heads),
        "ln_attn": L.layernorm_init(ch),
    }
    if context_dim:
        p["ln_cross"] = L.layernorm_init(ch)
        p["cross_q"] = L.linear_init(r[1], ch, ch, ("embed", "heads"))
        p["cross_k"] = L.linear_init(r[2], context_dim, ch, (None, "heads"))
        p["cross_v"] = L.linear_init(r[3], context_dim, ch, (None, "heads"))
        p["cross_o"] = L.linear_init(r[4], ch, ch, ("heads", "embed"))
    return p


def spatial_transformer_apply(cfg, p, x, context=None):
    b, h, w, c = x.shape
    hd = c // cfg.n_heads
    tokens = groupnorm_apply(p["norm"], x, cfg.groups).reshape(b, h * w, c)

    # self-attention
    t = L.layernorm_apply(p["ln_attn"], tokens)
    pa = p["attn"]
    q = L.linear_apply(pa["q"], t).reshape(b, h * w, cfg.n_heads, hd)
    k = L.linear_apply(pa["k"], t).reshape(b, h * w, cfg.n_heads, hd)
    v = L.linear_apply(pa["v"], t).reshape(b, h * w, cfg.n_heads, hd)
    a = L.dot_product_attention(q, k, v)
    tokens = tokens + L.linear_apply(pa["o"], a.reshape(b, h * w, c))

    # cross-attention against the conditioning sequence (text encoder states)
    if context is not None and "cross_q" in p:
        t = L.layernorm_apply(p["ln_cross"], tokens)
        s = context.shape[1]
        q = L.linear_apply(p["cross_q"], t).reshape(b, h * w, cfg.n_heads, hd)
        k = L.linear_apply(p["cross_k"], context).reshape(b, s, cfg.n_heads, hd)
        v = L.linear_apply(p["cross_v"], context).reshape(b, s, cfg.n_heads, hd)
        a = L.dot_product_attention(q, k, v)
        tokens = tokens + L.linear_apply(p["cross_o"], a.reshape(b, h * w, c))

    return x + tokens.reshape(b, h, w, c)


# ---------------------------------------------------------------------------------
# diffusers-exact blocks (diffusers_geometry=True; reference
# model_implementations/diffusers/unet.py:73 wraps the real
# UNet2DConditionModel — this is its module graph, TPU-native)
# ---------------------------------------------------------------------------------
def basic_transformer_init(rng, ch, n_heads, context_dim):
    """diffusers ``BasicTransformerBlock``: ln1+self-attn, ln2+cross-attn,
    ln3+GEGLU feed-forward. to_q/k/v carry no bias in diffusers; zero-bias
    here is numerically identical and keeps one linear layout."""
    r = jax.random.split(rng, 8)
    inner = 4 * ch
    ctx = context_dim or ch
    return {
        "ln1": L.layernorm_init(ch),
        "attn1": {"q": L.linear_init(r[0], ch, ch, ("embed", "heads")),
                  "k": L.linear_init(r[1], ch, ch, ("embed", "heads")),
                  "v": L.linear_init(r[2], ch, ch, ("embed", "heads")),
                  "o": L.linear_init(r[3], ch, ch, ("heads", "embed"))},
        "ln2": L.layernorm_init(ch),
        "attn2": {"q": L.linear_init(r[4], ch, ch, ("embed", "heads")),
                  "k": L.linear_init(r[5], ctx, ch, (None, "heads")),
                  "v": L.linear_init(r[6], ctx, ch, (None, "heads")),
                  "o": L.linear_init(r[7], ch, ch, ("heads", "embed"))},
        "ln3": L.layernorm_init(ch),
        # GEGLU: one projection to 2*inner, split into value and gate
        "ff_proj": L.linear_init(jax.random.fold_in(rng, 8), ch, 2 * inner,
                                 ("embed", "mlp")),
        "ff_out": L.linear_init(jax.random.fold_in(rng, 9), inner, ch,
                                ("mlp", "embed")),
    }


def _mha(q_p, k_p, v_p, o_p, xq, xkv, n_heads):
    b, s_q, c = xq.shape
    hd = c // n_heads
    q = L.linear_apply(q_p, xq).reshape(b, s_q, n_heads, hd)
    k = L.linear_apply(k_p, xkv).reshape(b, xkv.shape[1], n_heads, hd)
    v = L.linear_apply(v_p, xkv).reshape(b, xkv.shape[1], n_heads, hd)
    a = L.dot_product_attention(q, k, v)
    return L.linear_apply(o_p, a.reshape(b, s_q, c))


def basic_transformer_apply(cfg, p, tokens, context=None):
    t = L.layernorm_apply(p["ln1"], tokens)
    tokens = tokens + _mha(p["attn1"]["q"], p["attn1"]["k"], p["attn1"]["v"],
                           p["attn1"]["o"], t, t, cfg.n_heads)
    t = L.layernorm_apply(p["ln2"], tokens)
    kv = context if context is not None else t
    tokens = tokens + _mha(p["attn2"]["q"], p["attn2"]["k"], p["attn2"]["v"],
                           p["attn2"]["o"], t, kv, cfg.n_heads)
    t = L.layernorm_apply(p["ln3"], tokens)
    h = L.linear_apply(p["ff_proj"], t)
    val, gate = jnp.split(h, 2, axis=-1)
    return tokens + L.linear_apply(p["ff_out"], val * jax.nn.gelu(gate))


def spatial_transformer2d_init(rng, ch, n_heads, context_dim, depth=1):
    """diffusers ``Transformer2DModel`` (SD-1.x flavor): GroupNorm, 1x1-conv
    proj_in, ``depth`` BasicTransformerBlocks, 1x1-conv proj_out, residual."""
    r = jax.random.split(rng, depth + 2)
    return {
        "norm": groupnorm_init(ch),
        "proj_in": conv2d_init(r[0], ch, ch, kernel=1),
        "blocks": [basic_transformer_init(r[2 + i], ch, n_heads, context_dim)
                   for i in range(depth)],
        "proj_out": conv2d_init(r[1], ch, ch, kernel=1),
    }


def spatial_transformer2d_apply(cfg, p, x, context=None):
    b, h, w, c = x.shape
    res = x
    x = groupnorm_apply(p["norm"], x, cfg.groups)
    x = conv2d_apply(p["proj_in"], x)
    tokens = x.reshape(b, h * w, c)
    for blk in p["blocks"]:
        tokens = basic_transformer_apply(cfg, blk, tokens, context)
    x = conv2d_apply(p["proj_out"], tokens.reshape(b, h, w, c))
    return res + x


def vae_attention_init(rng, ch):
    """diffusers VAE mid-block ``Attention`` (single head, linear q/k/v/out
    over flattened tokens, GroupNorm in front)."""
    r = jax.random.split(rng, 4)
    return {"group_norm": groupnorm_init(ch),
            "q": L.linear_init(r[0], ch, ch, ("embed", "heads")),
            "k": L.linear_init(r[1], ch, ch, ("embed", "heads")),
            "v": L.linear_init(r[2], ch, ch, ("embed", "heads")),
            "o": L.linear_init(r[3], ch, ch, ("heads", "embed"))}


def vae_attention_apply(cfg, p, x):
    b, h, w, c = x.shape
    t = groupnorm_apply(p["group_norm"], x, cfg.groups).reshape(b, h * w, c)
    out = _mha(p["q"], p["k"], p["v"], p["o"], t, t, n_heads=1)
    return x + out.reshape(b, h, w, c)


# ---------------------------------------------------------------------------------
# UNet (conditional, diffusers UNet2DConditionModel-shaped)
# ---------------------------------------------------------------------------------
class SpatialUNet:
    """Compact conditional UNet: down blocks (resnet [+ attention] + stride-2
    conv), a middle block with attention, and up blocks with skip connections.

    Reference parity target: the model ``DSUNet`` wraps (diffusers
    ``UNet2DConditionModel``) — capability, not architecture-identical."""

    def __init__(self, config: SpatialConfig):
        self.config = config

    def init(self, rng):
        cfg = self.config
        if cfg.diffusers_geometry:
            return self._init_diffusers(rng)
        temb_dim = cfg.base_channels * 4
        chans = [cfg.base_channels * m for m in cfg.channel_mults]
        r = iter(jax.random.split(rng, 64))
        p = {
            "temb1": L.linear_init(next(r), cfg.base_channels, temb_dim,
                                   (None, None)),
            "temb2": L.linear_init(next(r), temb_dim, temb_dim,
                                   (None, None)),
            "conv_in": conv2d_init(next(r), cfg.in_channels, chans[0]),
        }
        down, ch = [], chans[0]
        for i, out_ch in enumerate(chans):
            blocks = []
            for _ in range(cfg.n_res_blocks):
                blk = {"res": resnet_block_init(next(r), ch, out_ch, temb_dim)}
                if i == len(chans) - 1:  # attention at the lowest resolution
                    blk["attn"] = spatial_transformer_init(
                        next(r), out_ch, cfg.n_heads, cfg.context_dim)
                blocks.append(blk)
                ch = out_ch
            down.append({"blocks": blocks,
                         "downsample": conv2d_init(next(r), ch, ch)
                         if i < len(chans) - 1 else None})
        p["down"] = down
        p["mid"] = {
            "res1": resnet_block_init(next(r), ch, ch, temb_dim),
            "attn": spatial_transformer_init(next(r), ch, cfg.n_heads,
                                             cfg.context_dim),
            "res2": resnet_block_init(next(r), ch, ch, temb_dim),
        }
        up = []
        for i, out_ch in reversed(list(enumerate(chans))):
            blocks = []
            for _ in range(cfg.n_res_blocks):
                blocks.append(
                    {"res": resnet_block_init(next(r), ch + out_ch, out_ch,
                                              temb_dim)})
                ch = out_ch
            up.append({"blocks": blocks,
                       "upsample": conv2d_init(next(r), ch, ch)
                       if i > 0 else None})
        p["up"] = up
        p["norm_out"] = groupnorm_init(ch)
        p["conv_out"] = conv2d_init(next(r), ch, cfg.out_channels)
        return p

    def _init_diffusers(self, rng):
        """EXACT diffusers UNet2DConditionModel graph: skips include conv_in
        and downsampler outputs, up blocks run n_res+1 resnets, attention per
        level (``attn_at``), Transformer2DModel blocks with proj_in/out."""
        cfg = self.config
        temb_dim = cfg.base_channels * 4
        chans = [cfg.base_channels * m for m in cfg.channel_mults]
        r = iter(jax.random.split(rng, 256))
        p = {
            "temb1": L.linear_init(next(r), cfg.base_channels, temb_dim,
                                   (None, None)),
            "temb2": L.linear_init(next(r), temb_dim, temb_dim, (None, None)),
            "conv_in": conv2d_init(next(r), cfg.in_channels, chans[0]),
        }
        skip_chs = [chans[0]]
        ch = chans[0]
        down = []
        for i, out_ch in enumerate(chans):
            blocks = []
            for _ in range(cfg.n_res_blocks):
                blk = {"res": resnet_block_init(next(r), ch, out_ch, temb_dim)}
                if cfg.attn_at(i):
                    blk["attn"] = spatial_transformer2d_init(
                        next(r), out_ch, cfg.n_heads, cfg.context_dim)
                blocks.append(blk)
                ch = out_ch
                skip_chs.append(ch)
            ds = None
            if i < len(chans) - 1:
                ds = conv2d_init(next(r), ch, ch)
                skip_chs.append(ch)
            down.append({"blocks": blocks, "downsample": ds})
        p["down"] = down
        p["mid"] = {
            "res1": resnet_block_init(next(r), ch, ch, temb_dim),
            "attn": spatial_transformer2d_init(next(r), ch, cfg.n_heads,
                                               cfg.context_dim),
            "res2": resnet_block_init(next(r), ch, ch, temb_dim),
        }
        up = []
        for k, out_ch in enumerate(reversed(chans)):
            level = len(chans) - 1 - k
            blocks = []
            for _ in range(cfg.n_res_blocks + 1):
                skip = skip_chs.pop()
                blk = {"res": resnet_block_init(next(r), ch + skip, out_ch,
                                                temb_dim)}
                if cfg.attn_at(level):
                    blk["attn"] = spatial_transformer2d_init(
                        next(r), out_ch, cfg.n_heads, cfg.context_dim)
                blocks.append(blk)
                ch = out_ch
            us = conv2d_init(next(r), ch, ch) if k < len(chans) - 1 else None
            up.append({"blocks": blocks, "upsample": us})
        p["up"] = up
        p["norm_out"] = groupnorm_init(ch)
        p["conv_out"] = conv2d_init(next(r), ch, cfg.out_channels)
        return p

    def _apply_diffusers(self, params, sample, timestep, ctx):
        cfg = self.config
        dtype = cfg.compute_dtype
        x = sample.astype(dtype)
        temb = timestep_embedding(jnp.asarray(timestep), cfg.base_channels)
        temb = L.linear_apply(params["temb2"], jax.nn.silu(
            L.linear_apply(params["temb1"], temb.astype(dtype))))
        x = conv2d_apply(params["conv_in"], x)
        skips = [x]
        for stage in params["down"]:
            for blk in stage["blocks"]:
                x = resnet_block_apply(cfg, blk["res"], x, temb)
                if "attn" in blk:
                    x = spatial_transformer2d_apply(cfg, blk["attn"], x, ctx)
                skips.append(x)
            if stage["downsample"] is not None:
                x = conv2d_apply(stage["downsample"], x, stride=2)
                skips.append(x)
        x = resnet_block_apply(cfg, params["mid"]["res1"], x, temb)
        x = spatial_transformer2d_apply(cfg, params["mid"]["attn"], x, ctx)
        x = resnet_block_apply(cfg, params["mid"]["res2"], x, temb)
        for stage in params["up"]:
            for blk in stage["blocks"]:
                skip = skips.pop()
                x = resnet_block_apply(
                    cfg, blk["res"], jnp.concatenate([x, skip], axis=-1), temb)
                if "attn" in blk:
                    x = spatial_transformer2d_apply(cfg, blk["attn"], x, ctx)
            if stage["upsample"] is not None:
                b, h, w, c = x.shape
                x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
                x = conv2d_apply(stage["upsample"], x)
        x = groupnorm_apply(params["norm_out"], x, cfg.groups, act="silu")
        return conv2d_apply(params["conv_out"], x).astype(dtype)

    def apply(self, params, sample, timestep, encoder_hidden_states=None):
        """sample: [b, h, w, in_ch] NHWC; timestep: [b]; encoder_hidden_states:
        [b, s, context_dim] or None. Returns the predicted noise [b, h, w, out_ch].
        """
        cfg = self.config
        if cfg.diffusers_geometry:
            ctx = None if encoder_hidden_states is None \
                else encoder_hidden_states.astype(cfg.compute_dtype)
            return self._apply_diffusers(params, sample, timestep, ctx)
        dtype = cfg.compute_dtype
        x = sample.astype(dtype)
        ctx = None if encoder_hidden_states is None \
            else encoder_hidden_states.astype(dtype)

        temb = timestep_embedding(jnp.asarray(timestep), cfg.base_channels)
        temb = L.linear_apply(params["temb2"], jax.nn.silu(
            L.linear_apply(params["temb1"], temb.astype(dtype))))

        x = conv2d_apply(params["conv_in"], x)
        skips = []
        for stage in params["down"]:
            for blk in stage["blocks"]:
                x = resnet_block_apply(cfg, blk["res"], x, temb)
                if "attn" in blk:
                    x = spatial_transformer_apply(cfg, blk["attn"], x, ctx)
                skips.append(x)
            if stage["downsample"] is not None:
                x = conv2d_apply(stage["downsample"], x, stride=2)

        x = resnet_block_apply(cfg, params["mid"]["res1"], x, temb)
        x = spatial_transformer_apply(cfg, params["mid"]["attn"], x, ctx)
        x = resnet_block_apply(cfg, params["mid"]["res2"], x, temb)

        for stage in params["up"]:
            for blk in stage["blocks"]:
                skip = skips.pop()
                if skip.shape[1] != x.shape[1]:  # resolution mismatch: upsample first
                    b, h, w, c = x.shape
                    x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
                x = resnet_block_apply(cfg, blk["res"],
                                       jnp.concatenate([x, skip], axis=-1), temb)
            if stage["upsample"] is not None:
                b, h, w, c = x.shape
                x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
                x = conv2d_apply(stage["upsample"], x)

        x = groupnorm_apply(params["norm_out"], x, cfg.groups, act="silu")
        return conv2d_apply(params["conv_out"], x).astype(dtype)


class SpatialVAEDecoder:
    """VAE decoder: latents [b, h, w, latent_ch] -> images
    [b, h * 2^(len(mults)-1), w * 2^(len(mults)-1), 3] — one stage per channel
    mult from deepest to shallowest with an x2 nearest upsample between stages
    (diffusers AutoencoderKL decoder geometry)."""

    def __init__(self, config: SpatialConfig):
        self.config = config

    def init(self, rng):
        cfg = self.config
        ch = cfg.base_channels * cfg.channel_mults[-1]
        # legacy geometry keeps its original split count: threefry subkeys
        # depend on n, so widening the split would silently change every
        # seeded legacy init
        r = iter(jax.random.split(rng, 96 if cfg.diffusers_geometry else 32))
        if cfg.diffusers_geometry:
            # EXACT diffusers AutoencoderKL decoder graph: post_quant_conv,
            # mid (res, single-head Attention, res), up blocks with
            # n_res_blocks+1 resnets each, upsamplers on all but the last
            p = {"post_quant_conv": conv2d_init(
                     next(r), cfg.in_channels, cfg.in_channels, kernel=1),
                 "conv_in": conv2d_init(next(r), cfg.in_channels, ch),
                 "mid": {"res1": resnet_block_init(next(r), ch, ch, 0),
                         "attn": vae_attention_init(next(r), ch),
                         "res2": resnet_block_init(next(r), ch, ch, 0)},
                 "up": []}
            stages = [cfg.base_channels * m for m in reversed(cfg.channel_mults)]
            for i, out_ch in enumerate(stages):
                blocks = []
                for _ in range(cfg.n_res_blocks + 1):
                    blocks.append(resnet_block_init(next(r), ch, out_ch, 0))
                    ch = out_ch
                p["up"].append({
                    "blocks": blocks,
                    "conv": conv2d_init(next(r), ch, ch)
                    if i < len(stages) - 1 else None,
                })
            p["norm_out"] = groupnorm_init(ch)
            p["conv_out"] = conv2d_init(next(r), ch, 3)
            return p
        p = {"conv_in": conv2d_init(next(r), cfg.in_channels, ch),
             "mid": {"res1": resnet_block_init(next(r), ch, ch, 0),
                     "attn": spatial_transformer_init(next(r), ch, cfg.n_heads, 0),
                     "res2": resnet_block_init(next(r), ch, ch, 0)},
             "up": []}
        stages = [cfg.base_channels * m for m in reversed(cfg.channel_mults)]
        for i, out_ch in enumerate(stages):
            p["up"].append({
                "res": resnet_block_init(next(r), ch, out_ch, 0),
                "conv": conv2d_init(next(r), out_ch, out_ch)
                if i < len(stages) - 1 else None,
            })
            ch = out_ch
        p["norm_out"] = groupnorm_init(ch)
        p["conv_out"] = conv2d_init(next(r), ch, 3)
        return p

    def apply(self, params, latents):
        cfg = self.config
        x = latents.astype(cfg.compute_dtype)
        if cfg.diffusers_geometry:
            x = conv2d_apply(params["post_quant_conv"], x)
            x = conv2d_apply(params["conv_in"], x)
            x = resnet_block_apply(cfg, params["mid"]["res1"], x)
            x = vae_attention_apply(cfg, params["mid"]["attn"], x)
            x = resnet_block_apply(cfg, params["mid"]["res2"], x)
            for stage in params["up"]:
                for res in stage["blocks"]:
                    x = resnet_block_apply(cfg, res, x)
                if stage["conv"] is not None:
                    b, h, w, c = x.shape
                    x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
                    x = conv2d_apply(stage["conv"], x)
            x = groupnorm_apply(params["norm_out"], x, cfg.groups, act="silu")
            return conv2d_apply(params["conv_out"], x)
        x = conv2d_apply(params["conv_in"], x)
        x = resnet_block_apply(cfg, params["mid"]["res1"], x)
        x = spatial_transformer_apply(cfg, params["mid"]["attn"], x)
        x = resnet_block_apply(cfg, params["mid"]["res2"], x)
        for stage in params["up"]:
            x = resnet_block_apply(cfg, stage["res"], x)
            if stage["conv"] is not None:
                b, h, w, c = x.shape
                x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
                x = conv2d_apply(stage["conv"], x)
        x = groupnorm_apply(params["norm_out"], x, cfg.groups, act="silu")
        return conv2d_apply(params["conv_out"], x)


# ---------------------------------------------------------------------------------
# DSUNet / DSVAE: the cuda-graph-equivalent serving wrappers
# ---------------------------------------------------------------------------------
class _JittedSpatial:
    """Jitted, shape-cached forward over a spatial module — one compiled XLA
    executable per input shape plays the role of the reference's captured CUDA
    graph (``DSUNet._create_cuda_graph``): after the first call, replay is a
    single dispatch with no Python in the loop."""

    def __init__(self, module, params=None, rng=None):
        self.module = module
        self.config = module.config
        if params is None:
            values, _ = L.split_params_axes(
                module.init(rng if rng is not None else jax.random.PRNGKey(0)))
            params = values
        self.params = params
        self._fns = {}

    def _call(self, key, fn, *args):
        if key not in self._fns:
            self._fns[key] = jax.jit(fn)
        return self._fns[key](self.params, *args)


class DSUNet(_JittedSpatial):
    def __call__(self, sample, timestep, encoder_hidden_states=None):
        sample = jnp.asarray(sample)
        ts = jnp.asarray(timestep)
        if ts.ndim == 0:
            ts = jnp.broadcast_to(ts, (sample.shape[0],))
        ctx = None if encoder_hidden_states is None else jnp.asarray(
            encoder_hidden_states)
        key = (sample.shape, None if ctx is None else ctx.shape)
        if ctx is None:
            return self._call(key, lambda p, s, t: self.module.apply(p, s, t),
                              sample, ts)
        return self._call(
            key, lambda p, s, t, c: self.module.apply(p, s, t, c),
            sample, ts, ctx)


class DSVAE(_JittedSpatial):
    def decode(self, latents):
        latents = jnp.asarray(latents)
        return self._call(latents.shape,
                          lambda p, z: self.module.apply(p, z), latents)

    __call__ = decode
