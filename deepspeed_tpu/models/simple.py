"""Tiny test models — the reference's fixture zoo (``tests/unit/simple_model.py``:
SimpleModel linear stacks used by most engine/ZeRO tests)."""

import jax
import jax.numpy as jnp

from . import layers as L
from .layers import Param


class SimpleModel:
    """Stack of linear+relu layers with an MSE head; batch = {"x": [b, d], "y": [b, d]}."""

    def __init__(self, hidden_dim=16, n_layers=2, compute_dtype=jnp.float32):
        self.hidden_dim = hidden_dim
        self.n_layers = n_layers
        self.compute_dtype = compute_dtype

    @property
    def config(self):
        return self

    def init(self, rng):
        params = {}
        for i, k in enumerate(jax.random.split(rng, self.n_layers)):
            params[f"layer_{i}"] = L.linear_init(
                k, self.hidden_dim, self.hidden_dim, ("embed", "mlp"), bias=True, stddev=0.1
            )
        return params

    def apply(self, params, x, deterministic=True, dropout_rng=None):
        h = x.astype(self.compute_dtype)
        for i in range(self.n_layers):
            h = L.linear_apply(params[f"layer_{i}"], h)
            if i < self.n_layers - 1:
                h = jax.nn.relu(h)
        return h

    def loss(self, params, batch, deterministic=True, dropout_rng=None):
        pred = self.apply(params, batch["x"], deterministic, dropout_rng)
        return jnp.mean(jnp.square(pred.astype(jnp.float32) - batch["y"].astype(jnp.float32)))


def random_batch(rng, batch_size, hidden_dim):
    kx, ky = jax.random.split(jax.random.PRNGKey(rng) if isinstance(rng, int) else rng)
    return {
        "x": jax.random.normal(kx, (batch_size, hidden_dim), jnp.float32),
        "y": jax.random.normal(ky, (batch_size, hidden_dim), jnp.float32),
    }
