"""Model zoo registry: the reference's per-architecture injection policies
(``module_inject/containers/{gpt2,opt,bloom,gptj,gptneox,...}.py``) become
TransformerConfig presets — the families differ in config, not code.

Size presets follow the published architectures (GPT-2 paper table 2; OPT paper
table 1; BLOOM config; LLaMA paper table 2).
"""

import jax.numpy as jnp

from .transformer import CausalLM, TransformerConfig


def gpt2_config(size="small", **overrides):
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=2, d_ff=512, max_seq_len=256),
        "small": dict(n_layers=12, d_model=768, n_heads=12, d_ff=3072),
        "medium": dict(n_layers=24, d_model=1024, n_heads=16, d_ff=4096),
        "large": dict(n_layers=36, d_model=1280, n_heads=20, d_ff=5120),
        "xl": dict(n_layers=48, d_model=1600, n_heads=25, d_ff=6400),
    }
    base = dict(
        vocab_size=50257, max_seq_len=1024, activation="gelu_new", norm="layernorm",
        position_embedding="learned", tie_embeddings=True, use_bias=True, prenorm=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def opt_config(size="125m", **overrides):
    presets = {
        "125m": dict(n_layers=12, d_model=768, n_heads=12, d_ff=3072),
        "350m": dict(n_layers=24, d_model=1024, n_heads=16, d_ff=4096),
        "1.3b": dict(n_layers=24, d_model=2048, n_heads=32, d_ff=8192),
        "2.7b": dict(n_layers=32, d_model=2560, n_heads=32, d_ff=10240),
        "6.7b": dict(n_layers=32, d_model=4096, n_heads=32, d_ff=16384),
        "13b": dict(n_layers=40, d_model=5120, n_heads=40, d_ff=20480),
        "30b": dict(n_layers=48, d_model=7168, n_heads=56, d_ff=28672),
    }
    base = dict(
        vocab_size=50272, max_seq_len=2048, activation="relu", norm="layernorm",
        position_embedding="learned", tie_embeddings=True, use_bias=True, prenorm=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def bloom_config(size="560m", **overrides):
    presets = {
        "560m": dict(n_layers=24, d_model=1024, n_heads=16, d_ff=4096),
        "1.7b": dict(n_layers=24, d_model=2048, n_heads=16, d_ff=8192),
        "3b": dict(n_layers=30, d_model=2560, n_heads=32, d_ff=10240),
        "7b": dict(n_layers=30, d_model=4096, n_heads=32, d_ff=16384),
    }
    base = dict(
        vocab_size=250880, max_seq_len=2048, activation="gelu", norm="layernorm",
        position_embedding="alibi", tie_embeddings=True, use_bias=True, prenorm=True,
        embed_layernorm=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def llama_config(size="7b", **overrides):
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, n_kv_heads=2, d_ff=352,
                     max_seq_len=256, vocab_size=1024),
        "7b": dict(n_layers=32, d_model=4096, n_heads=32, d_ff=11008),
        "13b": dict(n_layers=40, d_model=5120, n_heads=40, d_ff=13824),
    }
    base = dict(
        vocab_size=32000, max_seq_len=2048, activation="swiglu", norm="rmsnorm",
        position_embedding="rope", tie_embeddings=False, use_bias=False, prenorm=True,
        layernorm_eps=1e-6,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def mistral_config(size="7b", **overrides):
    """LLaMA-shaped with GQA + 32k rope base (Mistral paper)."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, n_kv_heads=2,
                     d_ff=352, max_seq_len=256, vocab_size=1024),
        "7b": dict(n_layers=32, d_model=4096, n_heads=32, n_kv_heads=8,
                   d_ff=14336, max_seq_len=32768),
    }
    base = dict(
        vocab_size=32000, activation="swiglu", norm="rmsnorm",
        position_embedding="rope", rope_base=10000.0, tie_embeddings=False,
        use_bias=False, prenorm=True, layernorm_eps=1e-5,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def qwen2_config(size="7b", **overrides):
    """LLaMA-shaped with GQA and attention bias on q/k/v only (o and the MLP
    stay unbiased) — mirrors module_inject/hf.py's qwen2 mapping so a
    from-scratch model and an imported checkpoint share one architecture."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, n_kv_heads=2,
                     d_ff=352, max_seq_len=256, vocab_size=1024),
        "7b": dict(n_layers=28, d_model=3584, n_heads=28, n_kv_heads=4,
                   d_ff=18944, max_seq_len=32768, vocab_size=152064),
    }
    base = dict(
        vocab_size=151936, activation="swiglu", norm="rmsnorm",
        position_embedding="rope", rope_base=1000000.0, tie_embeddings=False,
        use_bias=True, mlp_bias=False, prenorm=True, layernorm_eps=1e-6,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def gptj_config(size="6b", **overrides):
    """Parallel attn+mlp, shared LN, partial rotary, biased untied head."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, d_ff=512,
                     max_seq_len=256, vocab_size=1024, rotary_dim=16),
        "6b": dict(n_layers=28, d_model=4096, n_heads=16, d_ff=16384,
                   rotary_dim=64),
    }
    base = dict(
        vocab_size=50400, max_seq_len=2048, activation="gelu_new",
        norm="layernorm", position_embedding="rope", rotary_interleaved=True,
        tie_embeddings=False, head_bias=True, use_bias=False, mlp_bias=True,
        prenorm=True, parallel_attn_mlp=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def neox_config(size="20b", **overrides):
    """GPT-NeoX: parallel residual with separate norms, partial rotary."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, d_ff=512,
                     max_seq_len=256, vocab_size=1024, rotary_dim=8),
        "20b": dict(n_layers=44, d_model=6144, n_heads=64, d_ff=24576,
                    rotary_dim=24),
    }
    base = dict(
        vocab_size=50432, max_seq_len=2048, activation="gelu_exact",
        norm="layernorm", position_embedding="rope", tie_embeddings=False,
        use_bias=True, prenorm=True, parallel_attn_mlp=True,
        parallel_norm_split=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def falcon_config(size="7b", **overrides):
    """Falcon-7b geometry: parallel attn, one shared LN, multi-query, rope."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, d_ff=512,
                     max_seq_len=256, vocab_size=1024),
        "7b": dict(n_layers=32, d_model=4544, n_heads=71, d_ff=18176),
    }
    base = dict(
        vocab_size=65024, max_seq_len=2048, activation="gelu_exact",
        norm="layernorm", position_embedding="rope", n_kv_heads=1,
        tie_embeddings=True, use_bias=False, prenorm=True,
        parallel_attn_mlp=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def gpt_neo_config(size="1.3b", **overrides):
    """GPT-Neo: GPT-2-shaped with alternating banded local attention and
    UNSCALED attention logits."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=4, d_ff=512,
                     max_seq_len=256, vocab_size=1024,
                     local_attention_window=64),
        "1.3b": dict(n_layers=24, d_model=2048, n_heads=16, d_ff=8192),
        "2.7b": dict(n_layers=32, d_model=2560, n_heads=20, d_ff=10240),
    }
    base = dict(
        vocab_size=50257, max_seq_len=2048, activation="gelu_new",
        norm="layernorm", position_embedding="learned", tie_embeddings=True,
        use_bias=True, mlp_bias=True, prenorm=True,
        local_attention_window=256, attention_layers=("global", "local"),
        attn_scale=1.0,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def gpt2_moe_config(size="tiny", **overrides):
    """PR-MoE presets over the GPT-2 backbone (reference MoE tutorial
    configuration: GPT-style dense backbone + MoE FFNs with residual experts,
    ``moe/layer.py:16`` use_residual + noisy top-1 gating)."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=2, d_ff=512,
                     max_seq_len=256, n_experts=4),
        "small": dict(n_layers=12, d_model=768, n_heads=12, d_ff=3072,
                      n_experts=8),
        "medium": dict(n_layers=24, d_model=1024, n_heads=16, d_ff=4096,
                       n_experts=16),
    }
    base = dict(
        vocab_size=50257, max_seq_len=1024, activation="gelu_new",
        norm="layernorm", position_embedding="learned", tie_embeddings=True,
        use_bias=True, prenorm=True,
        moe_top_k=1, moe_use_residual=True, moe_use_rts=True,
        moe_noisy_gate_policy="rsample",
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def bert_config(size="base", **overrides):
    """Encoder presets (BERT paper table 1 geometry): post-norm, bidirectional,
    learned positions + segment embeddings, gelu, embed LN."""
    presets = {
        "tiny": dict(n_layers=2, d_model=128, n_heads=2, d_ff=512, max_seq_len=256),
        "base": dict(n_layers=12, d_model=768, n_heads=12, d_ff=3072),
        "large": dict(n_layers=24, d_model=1024, n_heads=16, d_ff=4096),
    }
    base = dict(
        vocab_size=30528,  # wordpiece 30522 padded to a multiple of 64
        max_seq_len=512, activation="gelu_exact", norm="layernorm",
        position_embedding="learned", tie_embeddings=True, use_bias=True,
        prenorm=False, causal=False, embed_layernorm=True, type_vocab_size=2,
        final_layernorm=False,  # post-norm blocks end with LN; BERT has no ln_f
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


MODEL_CONFIGS = {
    "gpt2": gpt2_config,
    "opt": opt_config,
    "bloom": bloom_config,
    "llama": llama_config,
    "mistral": mistral_config,
    "qwen2": qwen2_config,
    "gptj": gptj_config,
    "gpt_neox": neox_config,
    "gpt_neo": gpt_neo_config,
    "falcon": falcon_config,
    "bert": bert_config,
    "gpt2_moe": gpt2_moe_config,
}


def get_model(family, size=None, **overrides):
    """Build a model by family name, e.g. get_model('gpt2', 'medium').
    Encoder families (bert) return a MaskedLM; the rest a CausalLM."""
    from .transformer import MaskedLM

    if family not in MODEL_CONFIGS:
        raise ValueError(f"Unknown model family '{family}'. Available: {sorted(MODEL_CONFIGS)}")
    kwargs = {} if size is None else {"size": size}
    cfg = MODEL_CONFIGS[family](**kwargs, **overrides)
    cls = MaskedLM if not cfg.causal else CausalLM
    return cls(cfg)
